"""Wire-sparse gradient sync (mode='wire') on the 8-device CPU mesh.

The key guarantees: (1) shared-mask Random-K wire is bit-identical to its
simulate-mode counterpart (same mask derivation, k-element psum vs dense
psum); (2) error-feedback residual + transmitted == accumulated gradient;
(3) the analytic payload accounting reflects a genuinely smaller payload.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from tpu_compressed_dp.compat import shard_map

from tpu_compressed_dp.ops import wire
from tpu_compressed_dp.parallel.dp import CompressionConfig, init_ef_state, make_grad_sync

# ~6 min of shard_map compiles on the 1-core CI host — by far the largest
# module: excluded from the 870 s tier-1 budget (`-m 'not slow'`; the
# simulate-mode engine keeps tier-1 coverage via test_dp_sync/test_lowrank),
# runs in the unfiltered suite on real hardware
pytestmark = pytest.mark.slow


def run_sync(mesh, cfg, grads_per_dev, ef=None, seed=0):
    sync = make_grad_sync(cfg, "data")
    if ef is None:
        ef = init_ef_state(jax.tree.map(lambda g: g[0], grads_per_dev), cfg)

    def f(g, e):
        out, new_ef, _, stats = sync(
            jax.tree.map(lambda x: x[0], g), e, (), jax.random.key(seed))
        return out, new_ef, stats

    shard_spec = jax.tree.map(lambda _: P("data"), grads_per_dev)
    fn = shard_map(
        f,
        mesh=mesh,
        in_specs=(shard_spec, P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return fn(grads_per_dev, ef)


def make_grads(n=64, seed=0):
    k = jax.random.key(seed)
    return {
        "w": jax.random.normal(k, (8, n), jnp.float32),
        "b": jax.random.normal(jax.random.fold_in(k, 1), (8, 8), jnp.float32),
    }


class TestRandomKWire:
    @pytest.mark.parametrize("gran", ["layerwise", "entiremodel"])
    def test_matches_simulate_exactly(self, mesh8, gran):
        grads = make_grads()
        sim = CompressionConfig(
            method="randomk", ratio=0.25, granularity=gran, mode="simulate", shared_mask=True
        )
        wire = CompressionConfig(method="randomk", ratio=0.25, granularity=gran, mode="wire")
        out_s, _, _ = run_sync(mesh8, sim, grads)
        out_w, _, stats = run_sync(mesh8, wire, grads)
        for leaf in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(out_s[leaf]), np.asarray(out_w[leaf]), rtol=1e-6
            )
        # the wire payload is k elements, not n
        assert float(stats["sent_elems"]) < float(stats["dense_elems"])

    def test_payload_is_exactly_k(self, mesh8):
        grads = {"w": jnp.ones((8, 256), jnp.float32)}
        cfg = CompressionConfig(method="randomk", ratio=0.25, mode="wire")
        _, _, stats = run_sync(mesh8, cfg, grads)
        assert float(stats["sent_elems"]) == 64.0
        assert float(stats["sent_bits"]) == 64.0 * 32  # indices implied by shared key

    def test_rejects_per_worker_masks(self, mesh8):
        cfg = CompressionConfig(method="randomk", ratio=0.25, mode="wire", shared_mask=False)
        with pytest.raises(ValueError, match="shared_mask"):
            run_sync(mesh8, cfg, make_grads())


class TestTopKWire:
    def test_union_scatter_add(self, mesh8):
        # With distinct per-device top-k index sets, the result is the
        # world-average of per-device k-sparse vectors: verify against a
        # numpy model of exactly-k (no-ties) top-k.
        rng = np.random.default_rng(0)
        g = rng.normal(size=(8, 64)).astype(np.float32)
        cfg = CompressionConfig(method="topk", ratio=0.25, mode="wire")
        out, _, stats = run_sync(mesh8, cfg, {"w": jnp.asarray(g)})

        from tpu_compressed_dp.ops.compressors import topk_keep_count

        k = topk_keep_count(64, 0.25)
        exp = np.zeros(64, np.float32)
        for d in range(8):
            idx = np.argsort(-np.abs(g[d]))[:k]
            dense = np.zeros(64, np.float32)
            dense[idx] = g[d][idx]
            exp += dense
        exp /= 8
        np.testing.assert_allclose(np.asarray(out["w"]), exp, rtol=1e-5)
        assert float(stats["sent_elems"]) == float(k)
        assert float(stats["sent_bits"]) == k * 64.0  # values + explicit indices

    def test_error_feedback_residual(self, mesh8):
        grads = make_grads()
        cfg = CompressionConfig(method="topk", ratio=0.25, mode="wire", error_feedback=True)
        out, ef1, _ = run_sync(mesh8, cfg, grads)
        # device-0 residual: acc minus its own k-sparse transmission
        from tpu_compressed_dp.ops.compressors import topk_keep_count

        g0 = np.asarray(grads["w"])[0]
        k = topk_keep_count(64, 0.25)
        idx = np.argsort(-np.abs(g0))[:k]
        exp_res = g0.copy()
        exp_res[idx] = 0.0
        np.testing.assert_allclose(np.asarray(ef1["w"]), exp_res, rtol=1e-5)


class TestQuantizerWire:
    @pytest.mark.parametrize("method", ["terngrad", "qsgd"])
    def test_matches_simulate_with_per_worker_rng(self, mesh8, method):
        # Quantizer wire packs per-worker levels+scale; combined result equals
        # the simulate-mode psum of per-worker dequantised tensors when RNG
        # keys line up.  simulate uses per-worker keys by default; wire
        # derives the same leaf key without a worker fold, so compare with
        # shared_mask=True simulate (identical keys everywhere).
        grads = make_grads()
        sim = CompressionConfig(method=method, mode="simulate", shared_mask=True)
        wire = CompressionConfig(method=method, mode="wire", shared_mask=True)
        out_s, _, _ = run_sync(mesh8, sim, grads)
        out_w, _, stats = run_sync(mesh8, wire, grads)
        for leaf in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(out_s[leaf]), np.asarray(out_w[leaf]), rtol=1e-5, atol=1e-6
            )
        # quantizers send every element but at reduced width
        assert float(stats["sent_elems"]) == float(stats["dense_elems"])
        assert float(stats["sent_bits"]) < 32.0 * float(stats["dense_elems"])

    def test_ef_rejected_for_quantizers(self, mesh8):
        cfg = CompressionConfig(method="qsgd", mode="wire", error_feedback=True)
        with pytest.raises(ValueError, match="unbiased"):
            run_sync(mesh8, cfg, make_grads())


@pytest.mark.quick
class TestWirePacking:
    """Bit-packing primitives for the quantizer wire payloads (round 4)."""

    @pytest.mark.parametrize("n", [1, 3, 4, 7, 8, 1000])
    def test_ternary_roundtrip(self, n):
        rng = np.random.default_rng(n)
        levels = rng.integers(-1, 2, size=n).astype(np.int8)
        packed = wire.pack_ternary(jnp.asarray(levels))
        assert packed.dtype == jnp.uint8 and packed.shape == ((n + 3) // 4,)
        np.testing.assert_array_equal(
            np.asarray(wire.unpack_ternary(packed, n)), levels)

    @pytest.mark.parametrize("n", [1, 5, 8, 9, 1000])
    def test_bits_roundtrip(self, n):
        rng = np.random.default_rng(n)
        bits = rng.integers(0, 2, size=n).astype(bool)
        packed = wire.pack_bits(jnp.asarray(bits))
        assert packed.dtype == jnp.uint8 and packed.shape == ((n + 7) // 8,)
        np.testing.assert_array_equal(np.asarray(wire.unpack_bits(packed, n)), bits)

    def test_unpack_with_gather_axis(self):
        rng = np.random.default_rng(0)
        levels = rng.integers(-1, 2, size=(3, 10)).astype(np.int8)
        packed = jnp.stack([wire.pack_ternary(jnp.asarray(r)) for r in levels])
        np.testing.assert_array_equal(
            np.asarray(wire.unpack_ternary(packed, 10)), levels)

    @pytest.mark.parametrize("qstates", [15, 127, 200, 255, 1000])
    def test_qsgd_roundtrip(self, qstates):
        rng = np.random.default_rng(qstates)
        levels = rng.integers(-qstates, qstates + 1, size=333).astype(np.int16)
        payload = wire.qsgd_wire_pack(jnp.asarray(levels), qstates)
        widths = {p.dtype.itemsize for p in payload}
        if qstates <= 127:
            assert [p.dtype for p in payload] == [jnp.int8]
        elif qstates <= 255:
            assert [p.dtype for p in payload] == [jnp.uint8, jnp.uint8]
            assert payload[1].size == (333 + 7) // 8  # packed sign bitmap
        else:
            assert widths == {2}
        out = wire.qsgd_wire_unpack(payload, 333, qstates)
        np.testing.assert_array_equal(np.asarray(out), levels.astype(np.float32))


class TestMeasuredTransport:
    """`sent_bits` must equal 8 x the actual bytes handed to the collective
    for EVERY wire method — payload dtypes inspected at trace time, never
    assumed (VERDICT r3 #1; the TPU-static analog of the reference's NIC
    meter, `IMAGENET/training/meter.py:24-47`)."""

    CONFIGS = [
        dict(method="randomk", ratio=0.25),
        dict(method="topk", ratio=0.25),
        dict(method="blocktopk", ratio=0.25, block_size=16),
        dict(method="terngrad"),
        dict(method="terngrad", terngrad_chunk=16),   # chunked [nc] scales
        dict(method="qsgd", qstates=255),             # uint8 mags + sign bitmap
        dict(method="qsgd", qstates=127),             # int8 sign (x) level
        dict(method="qsgd", qstates=300),             # int16 fallback
        dict(method="thresholdv", threshold=0.5, wire_cap_ratio=0.25),
        dict(method="adaptive_threshold", wire_cap_ratio=0.25),
    ]

    @pytest.mark.parametrize("gran", ["layerwise", "entiremodel"])
    @pytest.mark.parametrize(
        "kw", CONFIGS, ids=[f"{c['method']}-{i}" for i, c in enumerate(CONFIGS)])
    def test_sent_bits_is_measured_payload_bytes(self, mesh8, monkeypatch, gran, kw):
        recorded = []

        real_gather = wire._all_gather
        real_psum = jax.lax.psum

        def spy_gather(x, axis_name, **kwargs):
            recorded.append(x.size * x.dtype.itemsize)
            return real_gather(x, axis_name, **kwargs)

        def spy_psum(x, axis_name, **kwargs):
            # payload psums only; the scalar world count is not a payload
            if hasattr(x, "ndim") and x.ndim >= 1:
                recorded.append(x.size * x.dtype.itemsize)
            return real_psum(x, axis_name, **kwargs)

        monkeypatch.setattr(wire, "_all_gather", spy_gather)
        monkeypatch.setattr(jax.lax, "psum", spy_psum)

        cfg = CompressionConfig(mode="wire", granularity=gran, **kw)
        _, _, stats = run_sync(mesh8, cfg, make_grads())
        assert recorded, "no collective payloads observed"
        assert float(stats["sent_bits"]) == 8.0 * sum(recorded)

    def test_terngrad_chunked_wire_matches_simulate(self, mesh8):
        # chunked scales (the entire-model NaN fix) through the WIRE path:
        # per-chunk fp32 scales travel with the int8 levels and the combined
        # result equals simulate mode with the same chunking
        grads = make_grads()
        sim = CompressionConfig(method="terngrad", mode="simulate",
                                granularity="entiremodel", shared_mask=True,
                                terngrad_chunk=16)
        wire = CompressionConfig(method="terngrad", mode="wire",
                                 granularity="entiremodel", shared_mask=True,
                                 terngrad_chunk=16)
        out_s, _, _ = run_sync(mesh8, sim, grads)
        out_w, _, stats = run_sync(mesh8, wire, grads)
        for leaf in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(out_s[leaf]), np.asarray(out_w[leaf]),
                rtol=1e-5, atol=1e-6)
        assert float(stats["sent_bits_allgather"]) > 0.0


class TestThresholdWire:
    """Fixed-capacity wire Threshold-V / Adaptive-Threshold (6/6 wire
    matrix): survivors pack into a cap-sized buffer; overflow stays in EF."""

    @pytest.mark.parametrize("method", ["thresholdv", "adaptive_threshold"])
    def test_matches_simulate_when_capacity_suffices(self, mesh8, method):
        grads = make_grads()
        kw = {"threshold": 0.8} if method == "thresholdv" else {}
        sim = CompressionConfig(method=method, granularity="layerwise", **kw)
        wire = CompressionConfig(method=method, granularity="layerwise",
                                 mode="wire", wire_cap_ratio=1.0, **kw)
        out_s, _, stats_s = run_sync(mesh8, sim, grads)
        out_w, _, stats_w = run_sync(mesh8, wire, grads)
        for k in out_s:
            np.testing.assert_allclose(np.asarray(out_s[k]), np.asarray(out_w[k]),
                                       rtol=1e-5, atol=1e-6)
        assert float(stats_w["threshold_overflow"]) == 0.0
        # both modes count the coordinates that actually survived
        assert float(stats_w["sent_elems"]) == pytest.approx(
            float(stats_s["sent_elems"]))

    def test_overflow_goes_to_ef(self, mesh8):
        # capacity 25% but ~50% of coordinates survive V: the clipped
        # survivors must land in the residual, and sent + residual must
        # reassemble the accumulated gradient exactly
        grads = make_grads(n=256)
        cfg = CompressionConfig(method="thresholdv", threshold=0.5,
                                granularity="entiremodel", mode="wire",
                                wire_cap_ratio=0.25, error_feedback=True)
        out, new_ef, stats = run_sync(mesh8, cfg, grads)
        assert float(stats["threshold_overflow"]) > 0.0
        # device-0 decomposition: gradient == sent + residual, exactly
        sent = {k: np.asarray(grads[k])[0] - np.asarray(new_ef[k])
                for k in grads}
        sent_flat = np.concatenate([sent[k].ravel() for k in sorted(sent)])
        nz = sent_flat[sent_flat != 0.0]
        # every coordinate that travelled exceeded V
        assert np.all(np.abs(nz) >= 0.5)
        # the cap-sized buffer filled completely (more survivors than cap)
        n_total = sum(np.asarray(v)[0].size for v in grads.values())
        cap = round(0.25 * n_total)
        assert len(nz) == cap

    def test_cap_billing_is_static(self, mesh8):
        # transport bills the full cap buffer even when half-empty
        grads = make_grads(n=256)
        cfg = CompressionConfig(method="thresholdv", threshold=100.0,
                                granularity="entiremodel", mode="wire",
                                wire_cap_ratio=0.25)
        _, _, stats = run_sync(mesh8, cfg, grads)
        n_total = 256 + 8
        cap = round(0.25 * n_total)
        assert float(stats["sent_bits"]) == cap * 64.0
        assert float(stats["sent_elems"]) == 0.0  # nothing survived V=100


class TestWireRejections:

    def test_dense_over_wire_falls_back_to_dense_allreduce(self, mesh8):
        # method=None has no sparse form; its wire format IS the dense psum.
        grads = make_grads()
        out, _, stats = run_sync(mesh8, CompressionConfig(method=None, mode="wire"), grads)
        np.testing.assert_allclose(
            np.asarray(out["w"]), np.asarray(grads["w"]).mean(0), rtol=1e-5
        )
        assert float(stats["sent_elems"]) == float(stats["dense_elems"])


class TestWirePerWorkerDither:
    @pytest.mark.parametrize("method", ["terngrad", "qsgd"])
    def test_per_worker_rng_matches_simulate(self, mesh8, method):
        # shared_mask=False must decorrelate quantisation noise across workers
        # in wire mode exactly as it does in simulate mode (same leaf_key
        # derivation with the worker fold).
        grads = make_grads()
        sim = CompressionConfig(method=method, mode="simulate", shared_mask=False)
        wire = CompressionConfig(method=method, mode="wire", shared_mask=False)
        out_s, _, _ = run_sync(mesh8, sim, grads)
        out_w, _, _ = run_sync(mesh8, wire, grads)
        for leaf in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(out_s[leaf]), np.asarray(out_w[leaf]), rtol=1e-5, atol=1e-6
            )

    def test_per_worker_differs_from_shared(self, mesh8):
        grads = make_grads()
        out_shared, _, _ = run_sync(
            mesh8, CompressionConfig(method="qsgd", mode="wire", shared_mask=True), grads
        )
        out_pw, _, _ = run_sync(
            mesh8, CompressionConfig(method="qsgd", mode="wire", shared_mask=False), grads
        )
        assert not np.allclose(np.asarray(out_shared["w"]), np.asarray(out_pw["w"]))


class TestWireTrainStep:
    def test_full_step_with_wire_randomk(self, mesh8):
        """The whole train step compiles and runs with a wire-sparse sync."""
        from tpu_compressed_dp.harness.dawn import MODELS
        from tpu_compressed_dp.models.common import init_model, make_apply_fn
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState
        from tpu_compressed_dp.train.step import make_train_step

        module = MODELS["resnet9"](0.125)
        params, stats = init_model(
            module, jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32)
        )
        opt = SGD(lr=0.01, momentum=0.9)
        cfg = CompressionConfig(
            method="randomk", ratio=0.1, mode="wire", error_feedback=True
        )
        state = TrainState.create(
            params, stats, opt.init(params), init_ef_state(params, cfg, 8), jax.random.key(1)
        )
        step = make_train_step(make_apply_fn(module), opt, cfg, mesh8)
        batch = {
            "input": jnp.zeros((16, 32, 32, 3), jnp.float32),
            "target": jnp.zeros((16,), jnp.int32),
        }
        state, metrics = step(state, batch)
        assert int(state.step) == 1
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["comm/sent_elems"]) < float(metrics["comm/dense_elems"])


class TestCheckSync:
    """The ``check_reduction`` analog: wire Random-K verifies cross-worker
    index agreement before the packed psum."""

    def _sync_with_keys(self, mesh8, key_fn):
        cfg = CompressionConfig(method="randomk", ratio=0.1, mode="wire",
                                check_sync=True)
        sync = make_grad_sync(cfg, "data")

        def f(g):
            return sync({"w": g[0]}, (), (), key_fn())[3]

        return shard_map(
            f, mesh=mesh8, in_specs=P("data"), out_specs=P(),
        )(jnp.ones((8, 4096)))

    def test_shared_key_agrees(self, mesh8):
        stats = self._sync_with_keys(mesh8, lambda: jax.random.key(0))
        assert float(stats["sync_agree"]) == 1.0

    def test_diverged_keys_detected(self, mesh8):
        def per_worker_key():
            return jax.random.fold_in(jax.random.key(0),
                                      jax.lax.axis_index("data"))

        # out_specs P() would reject the device-varying stats of diverged
        # masks at the type level; run with varying out to read the flag
        cfg = CompressionConfig(method="randomk", ratio=0.1, mode="wire",
                                check_sync=True)
        sync = make_grad_sync(cfg, "data")

        def f(g):
            stats = sync({"w": g[0]}, (), (), per_worker_key())[3]
            return stats["sync_agree"].reshape(1)

        agree = shard_map(f, mesh=mesh8, in_specs=P("data"),
                          out_specs=P("data"))(jnp.ones((8, 4096)))
        assert float(jnp.min(agree)) == 0.0


def test_packed_indices_underfull_mask_degrades_benignly():
    """Ranks beyond the mask's true count fill with index 0, matching
    jnp.nonzero(size=, fill_value=0) (the documented precondition guard)."""
    from tpu_compressed_dp.ops.wire import packed_indices_from_mask

    mask = jnp.zeros((1000,), bool).at[jnp.array([3, 500, 999])].set(True)
    idx = packed_indices_from_mask(mask, 8)
    np.testing.assert_array_equal(
        np.asarray(idx), np.asarray(jnp.nonzero(mask, size=8, fill_value=0)[0]))


@pytest.mark.quick
def test_packed_indices_exact_oracle_across_shapes():
    """Pack v2 (r5: fused row-starts gather + bf16 tri-matmul) must stay
    bit-identical to ``np.flatnonzero(mask)[:keep]`` padded with 0 — the
    oracle the round-5 rewrite was verified against — across row-boundary
    shapes, densities, and keep <, ==, > count."""
    from tpu_compressed_dp.ops.wire import packed_indices_from_mask

    rng = np.random.default_rng(7)
    for n in (5, 127, 128, 129, 1000, 4096):
        for frac in (0.02, 0.3, 0.9):
            mask = rng.random(n) < frac
            count = int(mask.sum())
            for keep in {1, max(1, count // 2), max(count, 1),
                         min(count + 3, n)}:
                got = np.asarray(
                    packed_indices_from_mask(jnp.asarray(mask), int(keep)))
                want = np.flatnonzero(mask)[:keep]
                want = np.pad(want, (0, keep - len(want)))
                np.testing.assert_array_equal(got, want,
                                              err_msg=f"n={n} frac={frac} "
                                                      f"keep={keep}")


class TestBlockTopKWire:
    """Net-new blocktopk: whole contiguous blocks travel as lane-aligned rows."""

    @pytest.mark.parametrize("gran", ["layerwise", "entiremodel"])
    def test_matches_simulate_exactly(self, mesh8, gran):
        grads = make_grads()
        sim = CompressionConfig(method="blocktopk", ratio=0.25, granularity=gran,
                                mode="simulate", block_size=16)
        wire = CompressionConfig(method="blocktopk", ratio=0.25, granularity=gran,
                                 mode="wire", block_size=16)
        out_s, _, _ = run_sync(mesh8, sim, grads)
        out_w, _, stats = run_sync(mesh8, wire, grads)
        for leaf in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(out_s[leaf]), np.asarray(out_w[leaf]), rtol=1e-6
            )
        assert float(stats["sent_elems"]) < float(stats["dense_elems"])

    def test_union_scatter_add(self, mesh8):
        # distinct per-device block sets -> world-average of block-sparse
        # vectors; verify against a numpy model
        rng = np.random.default_rng(1)
        g = rng.normal(size=(8, 64)).astype(np.float32)
        bs, ratio = 8, 0.25
        cfg = CompressionConfig(method="blocktopk", ratio=ratio, mode="wire", block_size=bs)
        out, _, stats = run_sync(mesh8, cfg, {"w": jnp.asarray(g)})

        from tpu_compressed_dp.ops.compressors import blocktopk_keep_blocks

        kb = blocktopk_keep_blocks(64, ratio, bs)
        exp = np.zeros(64, np.float32)
        for d in range(8):
            scores = (g[d].reshape(-1, bs) ** 2).sum(axis=1)
            sel = np.argsort(-scores)[:kb]
            dense = np.zeros(64, np.float32)
            for b in sel:
                dense[b * bs:(b + 1) * bs] = g[d][b * bs:(b + 1) * bs]
            exp += dense
        exp /= 8
        np.testing.assert_allclose(np.asarray(out["w"]), exp, rtol=1e-5)
        assert float(stats["sent_elems"]) == float(kb * bs)
        # 32-bit values + one 32-bit index per block
        assert float(stats["sent_bits"]) == kb * bs * (32.0 + 32.0 / bs)

    def test_error_feedback_residual(self, mesh8):
        grads = make_grads()
        bs = 16
        cfg = CompressionConfig(method="blocktopk", ratio=0.25, mode="wire",
                                block_size=bs, error_feedback=True)
        out, ef1, _ = run_sync(mesh8, cfg, grads)
        from tpu_compressed_dp.ops.compressors import blocktopk_keep_blocks

        g0 = np.asarray(grads["w"])[0]
        kb = blocktopk_keep_blocks(64, 0.25, bs)
        scores = (g0.reshape(-1, bs) ** 2).sum(axis=1)
        sel = np.argsort(-scores)[:kb]
        exp_res = g0.copy()
        for b in sel:
            exp_res[b * bs:(b + 1) * bs] = 0.0
        np.testing.assert_allclose(np.asarray(ef1["w"]), exp_res, rtol=1e-5)

    def test_small_leaf_dense_fallback(self, mesh8):
        # leaves <= block_size keep their only (padded) block; the wire path
        # must psum them dense rather than inflate to a padded block row
        grads = {"small": jnp.broadcast_to(jnp.arange(8, 10, 0.2, dtype=jnp.float32), (8, 10))}
        cfg = CompressionConfig(method="blocktopk", ratio=0.25, mode="wire",
                                block_size=256, error_feedback=True)
        out, ef1, stats = run_sync(mesh8, cfg, grads)
        assert float(stats["sent_elems"]) == 10.0  # n, not block_size
        np.testing.assert_allclose(np.asarray(out["small"]),
                                   np.asarray(grads["small"])[0], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(ef1["small"]), np.zeros(10))



    def test_small_bs_ef_immune_to_inf_in_sent_block(self, mesh8):
        """Covering-row EF (r5): a sent block containing inf must leave the
        residual finite and zeroed there — a scatter-multiply formulation
        would produce inf*0 = NaN and poison error feedback permanently
        (caught in r5 review; the mask-accumulate + where form is immune)."""
        from tpu_compressed_dp.ops import wire as wire_mod

        def f(flat):
            world = jax.lax.psum(1, "data")
            dense, ef, bits = wire_mod._leaf_sync_blocktopk(
                flat[0], 2, 8, "data", world, True)
            return dense, ef[None]

        g = np.random.default_rng(0).standard_normal(256).astype(np.float32)
        g[5] = np.inf
        gb = jnp.broadcast_to(jnp.asarray(g), (8, 256))
        dense, ef = shard_map(f, mesh=mesh8, in_specs=P("data"),
                              out_specs=(P(), P("data")))(gb)
        ef0 = np.asarray(ef)[0]
        assert np.isfinite(ef0).all()
        assert (ef0[0:8] == 0).all()

    def test_topk_poisoned_tail_keeps_payload_monotone(self, mesh8):
        """Poisoned-tail regression (histogram-edge clamp): a NaN in the
        gradient must not collapse the top-k histogram edges — pre-clamp a
        non-finite ``max(mag)`` made every edge NaN, the survivor count
        dropped below ``keep``, and the underfull pack padded duplicate
        index 0, voiding the sorted/unique scatter hints downstream.  The
        select must stay a veto (NaN never travels) with a full, strictly
        monotone payload."""
        from tpu_compressed_dp.ops import wire as wire_mod

        n, keep = 70000, 700
        g = np.random.default_rng(1).standard_normal(n).astype(np.float32)
        g[123] = np.nan
        flat = jnp.asarray(g)
        from tpu_compressed_dp.ops import kernels
        t = kernels.topk_threshold(jnp.abs(flat).astype(jnp.float32), keep)
        _, idx, count = wire_mod._select_pack(
            flat, jnp.abs(flat).astype(jnp.float32), t, keep)
        assert int(count) >= keep            # no underfull pack
        assert bool(wire_mod.packed_indices_monotone(idx))
        assert 123 not in np.asarray(idx)    # the NaN coordinate is vetoed

class TestBucketedWire:
    def test_bucketed_wire_matches_simulate(self, mesh8):
        # multi-leaf buckets through the wire path: same grouping and keys as
        # simulate mode, so shared-mask randomk agrees exactly
        grads = make_grads()
        kw = dict(method="randomk", ratio=0.25, granularity="bucketed",
                  bucket_mb=256 / 1e6, shared_mask=True)
        out_s, _, _ = run_sync(mesh8, CompressionConfig(mode="simulate", **kw), grads)
        out_w, _, stats = run_sync(mesh8, CompressionConfig(mode="wire", **kw), grads)
        for leaf in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(out_s[leaf]), np.asarray(out_w[leaf]), rtol=1e-6)
        assert float(stats["num_collectives"]) == 2.0
        assert float(stats["sent_elems"]) < float(stats["dense_elems"])

    def test_bucketed_wire_ef_topk(self, mesh8):
        grads = make_grads()
        cfg = CompressionConfig(method="topk", ratio=0.25, granularity="bucketed",
                                bucket_mb=256 / 1e6, mode="wire", error_feedback=True)
        out, ef1, _ = run_sync(mesh8, cfg, grads)
        from tpu_compressed_dp.ops.compressors import topk_keep_count

        g0 = np.asarray(grads["w"])[0]
        k = topk_keep_count(64, 0.25)
        idx = np.argsort(-np.abs(g0))[:k]
        exp_res = g0.copy()
        exp_res[idx] = 0.0
        np.testing.assert_allclose(np.asarray(ef1["w"]), exp_res, rtol=1e-5)


class TestSegPackWirePath:
    """The segmented shift-network kernel as the dispatched wire Top-K path
    (round 4): forced through the interpreter on the CPU mesh, the sync must
    match the default (global exact pack) path bit-for-bit when no segment
    overflows its cap, and conserve gradient mass into EF when one does."""

    def _patched(self, monkeypatch):
        import functools

        from tpu_compressed_dp.ops import kernels

        monkeypatch.setattr(kernels, "use_seg_pack", lambda n, k: True)
        monkeypatch.setattr(
            kernels, "seg_pack_by_threshold",
            functools.partial(kernels.seg_pack_by_threshold, interpret=True))

    def test_matches_default_path_no_overflow(self, mesh8, monkeypatch):
        grads = make_grads(n=700)
        cfg = CompressionConfig(method="topk", ratio=0.05,
                                granularity="entiremodel",
                                mode="wire", error_feedback=True)
        out_ref, ef_ref, stats_ref = run_sync(mesh8, cfg, grads)
        self._patched(monkeypatch)
        out_s, ef_s, stats_s = run_sync(mesh8, cfg, grads)
        for leaf in ("w", "b"):
            np.testing.assert_allclose(np.asarray(out_ref[leaf]),
                                       np.asarray(out_s[leaf]), rtol=1e-6)
            np.testing.assert_allclose(np.asarray(ef_ref[leaf]),
                                       np.asarray(ef_s[leaf]), rtol=1e-6)
        assert float(stats_s["sent_elems"]) == float(stats_ref["sent_elems"])
        assert float(stats_s["sent_bits"]) == float(stats_ref["sent_bits"])

    def test_ef_conserves_mass(self, mesh8, monkeypatch):
        # sent + residual must equal the accumulated gradient coordinatewise
        self._patched(monkeypatch)
        grads = make_grads(n=900, seed=4)
        cfg = CompressionConfig(method="topk", ratio=0.1,
                                granularity="entiremodel",
                                mode="wire", error_feedback=True)
        out, ef, _ = run_sync(mesh8, cfg, grads)
        # reconstruct: worker 0's contribution = its grads where sent
        # (psum-averaged output is checked in the parity test; here assert
        # residual + sent partition each worker's accumulated gradient)
        g0 = jax.tree.map(lambda g: g[0], grads)
        for leaf in ("w", "b"):
            acc = np.asarray(g0[leaf]).reshape(-1)
            res = np.asarray(ef[leaf]).reshape(-1)
            sent_coords = res == 0.0
            # every coordinate either kept whole in EF or fully sent
            np.testing.assert_allclose(res[~sent_coords], acc[~sent_coords])

    def test_surplus_reported_without_ef(self, mesh8, monkeypatch):
        self._patched(monkeypatch)
        grads = make_grads(n=700, seed=2)
        cfg = CompressionConfig(method="topk", ratio=0.02,
                                granularity="entiremodel", mode="wire",
                                error_feedback=False)
        _, _, stats = run_sync(mesh8, cfg, grads)
        assert "topk_surplus_dropped" in stats
