"""Scale-out digital twin (tpu_compressed_dp/twin/) — ISSUE 19.

The acceptance surface: every committed BENCH/MULTICHIP artifact parses
through the loader; a fit on planted alpha/beta/gamma recovers them; the
calibration fitted from the real records lands every step row within 15%
of its measured wall; the twin refuses to price an uncalibrated fabric;
the perf gate passes on the committed ``benchmarks/perf_pins.json`` and
trips on a deliberately inflated pin; ``bench/sweep.py --predict``
attaches the W-projection columns; the controller prices rungs through a
TwinPricer under ``--adaptive_model twin``; and the report/gate CLIs run.
"""

import copy
import dataclasses
import json
import os
import subprocess
import sys

import pytest

from tpu_compressed_dp.twin import (
    CalibRow, Calibration, CostModel, FabricParams, TwinPoint,
    UncalibratedFabricError, calibration_rows, check_pins,
    discover_record_paths, fit, load_calibration, load_pins, load_record_file,
    make_pin, predict_step_ms, save_calibration, schedule_for_point,
)
from tpu_compressed_dp.twin.model import (
    flat_schedule, hier_schedule, schedule_features,
)
from tpu_compressed_dp.twin.records import context_key, step_row

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PINS = os.path.join(REPO, "benchmarks", "perf_pins.json")


def repo_calib():
    rows = calibration_rows(REPO)
    assert rows, "no calibration rows found at the repo root"
    return fit(rows), rows


# ------------------------------------------------------------ record loader

@pytest.mark.quick
class TestRecordLoader:
    def test_every_committed_record_parses(self):
        """Every BENCH_r*/MULTICHIP_r* artifact loads, classifies, and
        normalizes without error — the satellite that keeps the twin's
        evidence base schema-honest."""
        paths = discover_record_paths(REPO)
        assert len(paths) >= 10, paths
        shapes = {}
        for p in paths:
            rf = load_record_file(p)
            shapes[rf.source] = rf.shape
            for row in rf.rows:
                assert row.kind in ("step", "phase")
                assert row.target_ms >= 0.0
                assert row.features, row.label
                if row.kind == "step":
                    assert row.context
        # the known artifact census: sweeps carry rows, verdicts carry none
        assert shapes["BENCH_r07.json"] == "sweep"
        assert shapes["BENCH_r09.json"] == "adaptive"
        assert shapes["BENCH_r12.json"] == "stream"
        assert all(s == "multichip" for n, s in shapes.items()
                   if n.startswith("MULTICHIP"))

    def test_loader_rejects_malformed(self, tmp_path):
        p = tmp_path / "BENCH_r99.json"
        p.write_text(json.dumps({"n": 1, "cmd": "x", "rc": 0, "records": [
            {"model": "m", "method": "none", "granularity": "g",
             "mode": "wire", "devices": 8, "batch": 64,
             "step_ms": "fast", "payload_mb_per_step": 1.0,
             "transport": "psum"}]}))
        with pytest.raises(ValueError, match="step_ms"):
            load_record_file(str(p))
        p.write_text(json.dumps({"n": 1, "cmd": "x", "rc": 0}))
        with pytest.raises(ValueError, match="unrecognized"):
            load_record_file(str(p))

    def test_context_key_pins_repeats_and_splits_configs(self):
        base = {"model": "resnet9", "method": "topk", "granularity": "e",
                "mode": "wire", "transport": "sharded", "ratio": 0.01,
                "devices": 8, "batch": 64}
        assert context_key(dict(base)) == context_key(dict(base))
        assert context_key(dict(base, dp_pods=2)) != context_key(dict(base))
        assert context_key(dict(base, pallas_mode="force")) \
            != context_key(dict(base))
        # powersgd keys on rank, not ratio
        pg = dict(base, method="powersgd", rank=4)
        assert "knob=4" in context_key(pg)


# ------------------------------------------------------------ the fitter

@pytest.mark.quick
class TestFit:
    def _synthetic_rows(self, alpha, beta, gamma, *, fabric="dcn"):
        """Rows generated from a planted (alpha, beta, gamma) + two known
        compute contexts — exactly recoverable by the lstsq."""
        truth = CostModel({fabric: FabricParams(alpha, beta, gamma, rows=1)})
        rows = []
        for i, (count, mb, w) in enumerate(
                [(1.0, 2.0, 8), (2.0, 0.5, 8), (4.0, 8.0, 4),
                 (1.0, 16.0, 16), (3.0, 1.0, 32)]):
            sched = [dataclasses.replace(
                flat_schedule(world=w, pods=2, count=count, psum_mb=mb)[0],
                fabric=fabric)]
            rows.append(CalibRow(
                source="synt", index=i, kind="phase", label=f"ph{i}",
                context=None, features=schedule_features(sched),
                target_ms=truth.comm_ms(sched)))
        for ctx, compute in (("a", 100.0), ("b", 250.0)):
            sched = flat_schedule(world=8, pods=2, count=2.0, psum_mb=4.0)
            rows.append(CalibRow(
                source="synt", index=10, kind="step", label=f"st-{ctx}",
                context=ctx, features=schedule_features(sched),
                target_ms=compute + truth.comm_ms(sched)))
        return rows

    def test_recovers_planted_params(self):
        calib = fit(self._synthetic_rows(3.0, 1.5, 0.25))
        p = calib.fabrics["dcn"]
        assert p.alpha_ms == pytest.approx(3.0, rel=1e-6)
        assert p.beta_ms_per_mb == pytest.approx(1.5, rel=1e-6)
        assert p.gamma_ms_per_hop == pytest.approx(0.25, rel=1e-6)
        assert calib.contexts["a"] == pytest.approx(100.0, rel=1e-6)
        assert calib.contexts["b"] == pytest.approx(250.0, rel=1e-6)
        assert all(abs(r.err_frac) < 1e-6 for r in calib.residuals)

    def test_clips_unphysical_params_to_zero(self):
        """Noise that would fit a negative coordinate gets clipped by the
        active-set pass; the step contexts re-solve exactly so the step
        residuals stay unpolluted."""
        rows = self._synthetic_rows(3.0, 0.0, 0.0)
        calib = fit(rows)
        p = calib.fabrics["dcn"]
        assert p.beta_ms_per_mb >= 0.0 and p.gamma_ms_per_hop >= 0.0
        for r in calib.residuals:
            if r.kind == "step":
                assert abs(r.err_frac) < 1e-6

    def test_fit_refuses_empty(self):
        with pytest.raises(ValueError, match="no calibration rows"):
            fit([])

    def test_save_load_roundtrip(self, tmp_path):
        calib = fit(self._synthetic_rows(3.0, 1.5, 0.25))
        path = str(tmp_path / "calib.json")
        save_calibration(calib, path)
        back = load_calibration(path)
        assert back.fabrics == calib.fabrics
        assert back.contexts == calib.contexts
        assert back.residuals == calib.residuals


# ------------------------------------------- modeled vs measured (real data)

class TestRealCalibration:
    def test_every_step_row_within_15_percent(self):
        """The headline acceptance bound: the twin fitted from the repo's
        own records reprices EVERY measured step row within 15%."""
        calib, rows = repo_calib()
        step = [r for r in calib.residuals if r.kind == "step"]
        assert len(step) >= 20
        for r in step:
            assert abs(r.err_frac) < 0.15, (
                f"{r.label}: modeled {r.modeled_ms:.1f} vs measured "
                f"{r.measured_ms:.1f} ({r.err_frac:+.1%})")
        assert calib.step_rms_frac < 0.15

    def test_both_fabrics_have_evidence(self):
        calib, _ = repo_calib()
        assert calib.fabrics["dcn"].rows > 0
        assert calib.fabrics["ici"].rows > 0
        for p in calib.fabrics.values():
            assert p.alpha_ms >= 0.0 and p.beta_ms_per_mb >= 0.0
            assert p.gamma_ms_per_hop >= 0.0

    def test_fit_is_deterministic(self):
        a, _ = repo_calib()
        b, _ = repo_calib()
        assert a.fabrics == b.fabrics and a.contexts == b.contexts


# ------------------------------------------------------------ forward model

@pytest.mark.quick
class TestForwardModel:
    MODEL = CostModel({"dcn": FabricParams(10.0, 1.0, 2.0, rows=5),
                       "ici": FabricParams(0.1, 0.05, 0.01, rows=5)})

    def test_refuses_uncalibrated_fabric(self):
        starved = CostModel({"ici": FabricParams(0.1, 0.05, 0.01, rows=5),
                             "dcn": FabricParams(rows=0)})
        pt = TwinPoint(world=8, transport="psum", n_params=1000, dp_pods=2)
        with pytest.raises(UncalibratedFabricError, match="dcn"):
            predict_step_ms(starved, pt)
        # the same point on a flat mesh bills ICI and prices fine
        flat = dataclasses.replace(pt, dp_pods=1)
        assert predict_step_ms(starved, flat) > 0.0

    def test_transport_schedules(self):
        n = 400_000
        for transport, pods, fabrics in (
                ("psum", 1, {"ici"}), ("psum", 2, {"dcn"}),
                ("all_gather", 2, {"dcn"}), ("sharded", 2, {"dcn"}),
                ("hierarchical", 2, {"ici", "dcn"})):
            method = "none" if transport == "psum" else "topk"
            sched = schedule_for_point(TwinPoint(
                world=8, transport=transport, n_params=n, dp_pods=pods,
                method=method, ratio=0.01))
            assert {c.fabric for c in sched} == fabrics, transport

    def test_hierarchical_beats_flat_at_scale(self):
        """The paper's point, restated by the twin: at large W the
        hierarchical transport's step time grows like pods while any flat
        collective grows like W."""
        def at(w, transport):
            return predict_step_ms(self.MODEL, TwinPoint(
                world=w, transport=transport, n_params=400_000,
                dp_pods=max(1, w // 64), method="topk", ratio=0.01))
        assert at(4096, "hierarchical") < at(4096, "all_gather")
        assert at(4096, "hierarchical") < at(4096, "sharded")
        # growth across a 16x scale-out: pods-like for hierarchical,
        # W-like for the flat collective
        hier_growth = at(4096, "hierarchical") / at(256, "hierarchical")
        flat_growth = at(4096, "all_gather") / at(256, "all_gather")
        assert hier_growth < flat_growth / 2.0

    def test_overlap_discount(self):
        pt = TwinPoint(world=8, transport="psum", n_params=400_000)
        full = predict_step_ms(self.MODEL, pt)
        half = predict_step_ms(self.MODEL, dataclasses.replace(
            pt, hideable_fraction=0.5))
        assert half == pytest.approx(full / 2.0)

    def test_hier_single_pod_degenerates_to_psum(self):
        sched = schedule_for_point(TwinPoint(
            world=8, transport="hierarchical", n_params=400_000,
            dp_pods=1, method="topk", ratio=0.01))
        assert [c.fabric for c in sched] == ["ici"]


# ------------------------------------------------------------ the perf gate

class TestPerfGate:
    def test_committed_pins_pass(self):
        """Tier-1 perf ratchet: every committed flagship pin re-prices
        within its tolerance through the CURRENT model + records."""
        doc = load_pins(PINS)
        assert len(doc["pins"]) >= 4
        calib, _ = repo_calib()
        results = check_pins(doc, calib)
        for r in results:
            assert r.ok, f"{r.name}: {r.note}"
            assert abs(r.frac_change) <= r.tol_frac

    def test_inflated_pin_trips_the_gate(self):
        """A modeled regression beyond tolerance fails: simulate one by
        deflating a pin's minted price (equivalently, the current model
        pricing the config >10% slower than when it was pinned)."""
        calib, _ = repo_calib()
        doc = copy.deepcopy(load_pins(PINS))
        doc["pins"][0]["modeled_step_ms"] = \
            float(doc["pins"][0]["modeled_step_ms"]) / 1.25
        bad = check_pins(doc, calib)
        assert not bad[0].ok and "regression" in bad[0].note
        # ...while a modeled DROP beyond tolerance only flags staleness
        doc2 = copy.deepcopy(load_pins(PINS))
        doc2["pins"][0]["modeled_step_ms"] = \
            float(doc2["pins"][0]["modeled_step_ms"]) * 1.25
        stale = check_pins(doc2, calib)
        assert stale[0].ok and "stale" in stale[0].note

    def test_vanished_context_is_unpriceable(self):
        calib, _ = repo_calib()
        doc = copy.deepcopy(load_pins(PINS))
        doc["pins"][0]["context"] = "model=ghost|method=none"
        res = check_pins(doc, calib)
        assert not res[0].ok and "unpriceable" in res[0].note

    def test_make_pin_roundtrip(self):
        calib, _ = repo_calib()
        doc = load_pins(PINS)
        pin = doc["pins"][0]
        minted = make_pin(pin["name"], pin["point"], pin["context"], calib)
        assert minted["modeled_step_ms"] == \
            pytest.approx(pin["modeled_step_ms"], rel=1e-6)


# ------------------------------------------------------- sweep --predict

class TestSweepPredict:
    def test_attach_prediction_columns(self):
        from tpu_compressed_dp.bench.sweep import (PREDICT_WORLDS,
                                                   attach_prediction)

        calib, _ = repo_calib()
        rec = json.load(open(os.path.join(REPO, "BENCH_r10.json")))[
            "records"][2]  # topk hierarchical W=8 pods=2
        rec = dict(rec)
        attach_prediction(rec, calib)
        assert rec["pred_basis"] == "context"
        assert rec["pred_step_ms"] == pytest.approx(
            float(rec["step_ms"]), rel=0.15)
        assert abs(rec["pred_err_frac"]) < 0.15
        assert rec["pred_err_bar_ms"] > 0.0
        for w in PREDICT_WORLDS:
            assert rec[f"pred_step_ms_w{w}"] is not None
        assert tuple(PREDICT_WORLDS) == (64, 256, 1024, 4096)

    def test_unseen_config_anchors_on_measured(self):
        from tpu_compressed_dp.bench.sweep import attach_prediction

        calib, _ = repo_calib()
        rec = json.load(open(os.path.join(REPO, "BENCH_r10.json")))[
            "records"][2]
        rec = dict(rec, batch=999)  # context never benchmarked
        attach_prediction(rec, calib)
        assert rec["pred_basis"] == "measured_anchor"
        assert rec["pred_err_frac"] == pytest.approx(0.0, abs=1e-9)


# ------------------------------------------------ control-plane integration

class TestTwinPricer:
    def _pricer(self, transport="psum", world=8, pods=1):
        from tpu_compressed_dp.control.signals import TwinPricer

        calib, rows = repo_calib()
        return TwinPricer(model=calib.model, world=world, pods=pods,
                          transport=transport, calib_rows=len(rows))

    def test_comm_pricing_is_monotone_in_bits(self):
        for transport in ("psum", "all_gather", "sharded", "hierarchical"):
            pr = self._pricer(transport=transport)
            lo, hi = pr.comm_ms(1e5), pr.comm_ms(1e6)
            assert 0.0 <= lo <= hi, transport

    def test_controller_requires_pricer_for_twin(self):
        from tpu_compressed_dp.control import ControlConfig, Controller

        cfg = ControlConfig(method="topk", rungs=(0.5, 0.25),
                            budget_ms=1.0, model="twin")
        with pytest.raises(ValueError, match="TwinPricer"):
            Controller(cfg)

    def test_config_rejects_unknown_model(self):
        from tpu_compressed_dp.control import ControlConfig

        with pytest.raises(ValueError, match="flat|twin"):
            ControlConfig(method="topk", rungs=(0.5, 0.25), budget_ms=1.0,
                          model="oracle")

    def test_twin_signal_and_metrics(self):
        from tpu_compressed_dp.control import (ControlConfig, Controller,
                                               init_control_state)

        cfg = ControlConfig(method="topk", rungs=(0.5, 0.25, 0.125),
                            window=4, deadband=0.25, budget_ms=1.0,
                            bandwidth_mbps=100.0, model="twin")
        c = Controller(cfg, pricer=self._pricer())
        cs = init_control_state(cfg)
        sig = c.window_signals(mean_bits=4e5)
        assert sig.comm_ms == pytest.approx(
            self._pricer().comm_ms(4e5))
        # mid-window (accumulators live): the twin stats are exported
        cs, _ = c.tick(cs, applied=2, signals=sig)
        m = c.metrics(cs)
        assert "twin/pred_step_ms" in m and "twin/calib_rows" in m
        assert m["twin/calib_rows"] > 0
        # flat default emits no twin stats
        flat = Controller(dataclasses.replace(cfg, model="flat"))
        fs = init_control_state(cfg)
        fs, _ = flat.tick(fs, applied=2,
                          signals=flat.window_signals(mean_bits=4e5))
        assert not any(k.startswith("twin/") for k in flat.metrics(fs))

    def test_window_close_prices_through_twin(self):
        from tpu_compressed_dp.control import (ControlConfig, Controller,
                                               init_control_state)

        cfg = ControlConfig(method="topk", rungs=(0.5, 0.25, 0.125),
                            window=2, deadband=0.25, budget_ms=1.0,
                            bandwidth_mbps=100.0, model="twin")
        pr = self._pricer()
        c = Controller(cfg, pricer=pr)
        cs = init_control_state(cfg)
        cs, (dec,) = c.tick(cs, applied=2,
                            signals=c.window_signals(mean_bits=4e5))
        assert dec.comm_ms == pytest.approx(pr.comm_ms(4e5))

    def test_build_twin_pricer_from_args(self):
        import argparse

        from tpu_compressed_dp.harness.loop import build_twin_pricer

        ns = argparse.Namespace(adaptive_model="twin", twin_records=REPO,
                                dp_pods=2)
        comp = argparse.Namespace(mode="wire", transport="allgather")
        pr = build_twin_pricer(ns, comp, world=8)
        assert pr is not None and pr.transport == "all_gather"
        assert pr.world == 8 and pr.pods == 2 and pr.calib_rows > 0
        ns_flat = argparse.Namespace(adaptive_model="flat")
        assert build_twin_pricer(ns_flat, None, world=8) is None

    def test_twin_stats_registered_and_lint_clean(self):
        from tpu_compressed_dp.analysis.hostlint import STAT_FAMILIES
        from tpu_compressed_dp.obs.registry import is_declared

        for name in ("twin/pred_step_ms", "twin/pred_err_frac",
                     "twin/calib_rows"):
            assert is_declared(name), name
        assert "twin" in STAT_FAMILIES


# ------------------------------------------------------------ the CLIs

class TestTwinCLIs:
    def _run(self, argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run([sys.executable] + argv, cwd=REPO, env=env,
                              capture_output=True, text=True, timeout=300)

    def test_twin_report_smoke(self):
        r = self._run(["tools/twin_report.py", "--records", "."])
        assert r.returncode == 0, r.stderr
        assert "calibration" in r.stdout
        assert "modeled vs measured (step rows)" in r.stdout
        for w in (64, 256, 1024, 4096):
            assert f"W={w}" in r.stdout

    def test_twin_report_gate_cli(self):
        r = self._run(["tools/twin_report.py", "--records", ".", "--gate"])
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 failing" in r.stdout

    def test_control_report_twin_column(self):
        """control_report's modeled-vs-measured audit: decision rows gain
        a twin-priced comm column next to the flat price."""
        import tools.control_report as cr
        from tpu_compressed_dp.obs.export import SCHEMA_VERSION

        events = [
            {"v": SCHEMA_VERSION, "kind": "run_start",
             "transport": "allgather", "devices": 8, "dp_pods": 2},
            {"v": SCHEMA_VERSION, "kind": "control_decision", "index": 0,
             "applied": 8, "updates": 8, "knob": "ratio", "rung_to": 0,
             "value_to": 0.5, "comm_ms": 4.0, "budget_ms": 1.0,
             "bits": 4e5, "direction": "hold"},
        ]
        pricer = cr.build_pricer(events, REPO)
        assert pricer.transport == "all_gather"
        assert pricer.world == 8 and pricer.pods == 2
        rows = [{"bits": 4e5}, {"note": "no bits"}]
        cr.attach_twin_price(rows, pricer)
        assert rows[0]["twin_comm_ms"] == pytest.approx(
            pricer.comm_ms(4e5))
        assert "twin_comm_ms" not in rows[1]
        text = cr.render_report(events, pricer=pricer)
        assert "twin ms" in text and "twin: W=8 pods=2" in text
        # without the pricer the report stays byte-identical to before
        assert "twin" not in cr.render_report(events)

    def test_twin_report_json(self):
        r = self._run(["tools/twin_report.py", "--records", ".", "--json",
                       "--gate"])
        assert r.returncode == 0, r.stderr
        doc = json.loads(r.stdout)
        assert set(doc["fabrics"]) == {"dcn", "ici"}
        assert doc["projection"] and doc["gate"]
        assert all(g["ok"] for g in doc["gate"])
