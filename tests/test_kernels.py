"""Pallas kernel tests (interpret mode on the CPU mesh backend).

The kernels must agree with the pure-JAX reference operators in
:mod:`tpu_compressed_dp.ops.compressors`:
  * Top-K histogram threshold selects exactly the same coordinate set as the
    exact ``lax.top_k`` threshold for tie-free data;
  * the fused quantizers produce levels with the right range, sign, and
    (for QSGD) unbiasedness, from their own hardware-PRNG stream.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_compressed_dp import compat
from tpu_compressed_dp.ops import compressors, kernels


@pytest.fixture(autouse=True)
def _pallas_off_dispatch():
    # unit-test the kernels directly (interpret mode); keep auto-dispatch from
    # engaging inside compressor calls on the CPU backend
    kernels.set_pallas_mode("off")
    yield
    kernels.set_pallas_mode("auto")


class TestTopkThreshold:
    def _exact(self, mag, keep):
        return jax.lax.top_k(mag, keep)[0][-1]

    @pytest.mark.parametrize("n,keep", [(5000, 500), (8192, 1), (300, 299), (70000, 7000)])
    def test_matches_exact_selection(self, n, keep):
        mag = jnp.abs(jax.random.normal(jax.random.key(n + keep), (n,)))
        t = kernels._topk_threshold_pallas(mag, keep, interpret=True)
        exact = self._exact(mag, keep)
        # identical coordinate sets (data is tie-free at kernel resolution)
        np.testing.assert_array_equal(np.asarray(mag >= t), np.asarray(mag >= exact))
        assert int(jnp.sum(mag >= t)) == keep

    @pytest.mark.slow  # ~9 s each on the 1-core host (multi-MB interpret runs)
    @pytest.mark.parametrize("keep_frac", [0.01, 0.1])
    def test_sampled_init_large_n(self, keep_frac):
        # large n + moderate keep engages the sampled-init fast path (slab
        # subsample -> quantile-edge round -> 3 narrow rounds; the gate
        # requires the sample to be <= n/16, true here); the count >= keep
        # guarantee and tie-level surplus must hold there too
        n = 1 << 22
        keep = max(1, int(n * keep_frac))
        mag = jnp.abs(jax.random.normal(jax.random.key(7), (n,)))
        t = kernels._topk_threshold_pallas(mag, keep, interpret=True)
        cnt = int(jnp.sum(mag >= t))
        assert cnt >= keep
        assert cnt <= keep + 256  # surplus at final-bin resolution only

    def test_small_or_dense_keep_uses_exact_full_path(self):
        # mid-size tensors (sample can't be << data) must take the exact
        # full-range histogram: tie-exact count
        n = 1 << 18
        keep = 262
        mag = jnp.abs(jax.random.normal(jax.random.key(9), (n,)))
        t = kernels._topk_threshold_pallas(mag, keep, interpret=True)
        assert int(jnp.sum(mag >= t)) == keep

    @pytest.mark.slow  # ~9 s on the 1-core host
    def test_sampled_init_adversarial_layout_keeps_guarantee(self):
        # the slab sample reads the first 128 lanes of each C-block (C=4096
        # for this n/keep); hide MORE than `keep` spikes in the unsampled
        # lanes so every sample quantile is noise-level and the k-th
        # magnitude lands in the huge top bin.  The structural guarantee
        # (count >= keep; refine rounds shrink the surplus) must survive
        # this worst case — there is deliberately no data-dependent branch
        # (a cond would run both sides under shard_map).
        n = 1 << 22
        keep = int(n * 0.1)
        base = jnp.abs(jax.random.normal(jax.random.key(8), (n,))) * 1e-3
        lanes = jnp.arange(n) % 4096
        spike = lanes >= 128  # every lane the slab sample never reads
        vals = 100.0 + (jnp.arange(n) % 977).astype(jnp.float32) / 977.0
        mag = jnp.where(spike, vals, base)
        t = kernels._topk_threshold_pallas(mag, keep, interpret=True)
        cnt = int(jnp.sum(mag >= t))
        assert cnt >= keep
        # degraded-case surplus is bounded by the selected bin's population
        # after 16^3 refinement (~4% here); EF reabsorbs the boundary
        # elements the fixed-size pack then drops
        assert cnt <= int(keep * 1.05)
        assert float(t) > 1.0  # found the spikes, not the base noise

    def test_ties_all_kept(self):
        mag = jnp.ones((4096,))
        t = kernels._topk_threshold_pallas(mag, 100, interpret=True)
        assert int(jnp.sum(mag >= t)) == 4096  # reference keeps ties (core.py:183)

    def test_all_zero(self):
        mag = jnp.zeros((2048,))
        t = kernels._topk_threshold_pallas(mag, 10, interpret=True)
        assert int(jnp.sum(mag >= t)) == 2048

    def test_keep_all_shortcut(self):
        mag = jnp.abs(jax.random.normal(jax.random.key(0), (128,)))
        assert float(kernels.topk_threshold(mag, 128)) == 0.0

    def test_dispatch_cpu_is_exact(self):
        g = jax.random.normal(jax.random.key(1), (1 << 17,))
        out = compressors.top_k(g, ratio=0.01)
        keep = compressors.topk_keep_count(g.shape[0], 0.01)
        assert int(jnp.count_nonzero(out)) == keep


@pytest.mark.skipif(
    not compat.HAS_TPU_INTERPRET,
    reason="quantizer kernels draw from the TPU hardware PRNG; the stock "
           "HLO interpreter on this jax release has no prng_seed lowering")
class TestQuantKernels:
    """Interpret-mode PRNG is a zero stub on CPU (dither u == 0), so these
    cover everything EXCEPT the dither draw: with u=0 QSGD degenerates to
    deterministic truncation — range, sign, dtype, and scale stay testable.
    The dither itself (unbiasedness, per-key determinism) is validated on
    real hardware by ``test_kernels_on_tpu_chip``."""

    def test_qsgd_levels_range_sign(self):
        g = jax.random.normal(jax.random.key(2), (20000,))
        levels, scale = kernels.qsgd_quantize(g, jax.random.key(3), qstates=255,
                                              interpret=True)
        assert levels.dtype == jnp.int16
        lv = np.asarray(levels)
        assert np.all(np.abs(lv) <= 255)
        nz = lv != 0
        assert np.all(np.sign(lv[nz]) == np.sign(np.asarray(g)[nz]))
        # u=0 -> levels == floor(|g|/norm * s) exactly
        ref = np.floor(np.abs(np.asarray(g)) / np.linalg.norm(np.asarray(g)) * 255)
        np.testing.assert_array_equal(np.abs(lv), ref)
        assert float(scale) == pytest.approx(
            float(jnp.linalg.norm(g)) / 255, rel=1e-6)

    def test_terngrad_levels(self):
        g = jax.random.normal(jax.random.key(7), (12000,))
        levels, scale = kernels.terngrad_quantize(g, jax.random.key(8), interpret=True)
        assert levels.dtype == jnp.int8
        lv = np.asarray(levels)
        assert set(np.unique(lv)) <= {-1, 0, 1}
        nz = lv != 0
        assert np.all(np.sign(lv[nz]) == np.sign(np.asarray(g)[nz]))
        assert float(scale) == pytest.approx(float(jnp.max(jnp.abs(g))))

    def test_zero_grad_maps_to_zero(self):
        g = jnp.zeros((8192,))
        lq, sq = kernels.qsgd_quantize(g, jax.random.key(9), interpret=True)
        lt, st = kernels.terngrad_quantize(g, jax.random.key(9), interpret=True)
        assert not np.asarray(lq).any() and not np.asarray(lt).any()
        assert float(sq) == 0.0 and float(st) == 0.0


def _tpu_present() -> bool:
    import shutil, subprocess, sys

    code = (
        "import os, jax, sys;"
        "sys.exit(0 if jax.devices()[0].platform != 'cpu' else 1)"
    )
    env = {k: v for k, v in __import__("os").environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        return subprocess.run([sys.executable, "-c", code], env=env,
                              timeout=120, capture_output=True).returncode == 0
    except Exception:
        return False


@pytest.mark.skipif(not _tpu_present(), reason="no TPU attached")
def test_kernels_on_tpu_chip():
    """Compiled (non-interpret) kernels on the real chip: exact top-k set,
    QSGD unbiasedness + per-key determinism of the hardware-PRNG dither."""
    import os, subprocess, sys

    script = r"""
import jax, numpy as np, jax.numpy as jnp
from tpu_compressed_dp.ops import kernels
g = jax.random.normal(jax.random.key(1), (1 << 20,))
mag = jnp.abs(g); keep = 10000
t = jax.jit(lambda m: kernels._topk_threshold_pallas(m, keep))(mag)
exact = jax.lax.top_k(mag, keep)[0][-1]
assert (np.asarray(mag >= t) == np.asarray(mag >= exact)).all()
assert int((mag >= t).sum()) == keep
f = jax.jit(lambda g, k: kernels.qsgd_quantize(g, k, qstates=255))
lv, sc = f(g, jax.random.key(2))
lv = np.asarray(lv); sc = float(sc)
err = sc * lv - np.asarray(g)
assert abs(err.mean()) < 3 * sc / np.sqrt(len(g)), err.mean()
assert (np.asarray(f(g, jax.random.key(2))[0]) == lv).all()
assert not (np.asarray(f(g, jax.random.key(3))[0]) == lv).all()
print("OK")
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + (
        os.pathsep + env["PYTHONPATH"] if "PYTHONPATH" in env else "")
    res = subprocess.run([sys.executable, "-c", script], env=env, timeout=560,
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "OK" in res.stdout


class TestFusedSparsify:
    """The simulate-mode fused epilogue must match the unfused
    where/subtract/count chain exactly."""

    @pytest.mark.parametrize("want_ef", [True, False])
    def test_matches_unfused(self, want_ef):
        n = 5000
        acc = jax.random.normal(jax.random.key(1), (n,))
        t = kernels.topk_threshold(jnp.abs(acc), 500)
        comp, new_ef, cnt = kernels.fused_sparsify(acc, t, want_ef=want_ef,
                                                   interpret=True)
        exp_comp = jnp.where(jnp.abs(acc) >= t, acc, 0.0)
        np.testing.assert_allclose(np.asarray(comp), np.asarray(exp_comp),
                                   rtol=1e-6)
        if want_ef:
            np.testing.assert_allclose(np.asarray(new_ef),
                                       np.asarray(acc - exp_comp), rtol=1e-6)
        else:
            assert new_ef is None
        assert int(cnt) == int(jnp.count_nonzero(exp_comp))

    def test_zero_threshold_counts_nonzeros_only(self):
        # t == 0 keeps every real coordinate; the pad tail AND exact zeros
        # must not count as sent (count_nonzero parity with the unfused path)
        n = 200  # far from a chunk multiple
        acc = jnp.ones((n,)).at[7].set(0.0).at[100].set(0.0)
        comp, new_ef, cnt = kernels.fused_sparsify(
            acc, jnp.float32(0.0), interpret=True)
        assert int(cnt) == n - 2
        np.testing.assert_allclose(np.asarray(comp), np.asarray(acc))
        np.testing.assert_allclose(np.asarray(new_ef), np.zeros(n))


def test_topk_threshold_jnp_fallback_guarantee():
    """The pure-jnp histogram (the >int32 fallback) keeps the structural
    count(mag >= t) >= keep guarantee with tie-resolution surplus only."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_compressed_dp.ops.kernels import _topk_threshold_jnp

    for seed, n, keep in [(0, 4096, 41), (1, 1000, 1), (2, 8192, 8000)]:
        mag = jnp.abs(jax.random.normal(jax.random.key(seed), (n,)))
        t = _topk_threshold_jnp(mag, keep)
        cnt = int(jnp.sum(mag >= t))
        assert cnt >= keep
        exact = float(jax.lax.top_k(mag, keep)[0][-1])
        # threshold within the refinement resolution of the exact k-th value
        assert float(t) <= exact
        assert cnt <= keep + max(8, int(0.01 * n))


class TestPackByThreshold:
    """Fused wire-pack kernel (VERDICT r2 #4): correct but slower than the
    unfused chain on this chip — kept in-tree as a measured negative result
    (benchmarks/pack_kernel_r3.txt), NOT dispatched by the wire path."""

    def _check(self, n, keep, seed=0):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_compressed_dp.ops import kernels as K

        rng = np.random.default_rng(seed)
        acc = jnp.asarray(rng.standard_normal(n), jnp.float32)
        t = jnp.asarray(
            np.partition(np.abs(np.asarray(acc)), n - keep)[n - keep],
            jnp.float32)
        vals, idx, ef, count = K.pack_by_threshold(
            acc, t, keep, want_ef=True, interpret=True)
        mask = np.asarray(jnp.abs(acc) >= t)
        a = np.asarray(acc)
        dense = np.zeros(n, np.float64)
        np.add.at(dense, np.asarray(idx), np.asarray(vals, np.float64))
        np.testing.assert_allclose(dense, np.where(mask, a, 0.0),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(ef), np.where(mask, 0.0, a))
        assert int(count) == mask.sum()
        nz = np.asarray(vals) != 0
        assert nz.sum() == mask.sum()
        assert np.all(np.diff(np.asarray(idx)[nz]) > 0)  # ascending payload

    def test_small_pack(self, monkeypatch):
        from tpu_compressed_dp.ops import kernels as K

        monkeypatch.setattr(K, "_PACK_ROWS", 16)  # interpreter-tractable
        self._check(5000, 50)

    def test_multiblock_and_ragged(self, monkeypatch):
        from tpu_compressed_dp.ops import kernels as K

        monkeypatch.setattr(K, "_PACK_ROWS", 16)
        self._check(17000, 700)   # multi-block + ragged tail
        self._check(40000, 350)

    def test_payload_slots_accounting(self):
        from tpu_compressed_dp.ops import kernels as K

        P = K.pack_payload_slots(5_000_000, 50_000)
        blocks = -(-5_000_000 // (K._PACK_ROWS * 128))
        assert P == -(-50_000 // 128) * 128 + blocks * 128

    def test_capacity_truncation_conserves_mass(self, monkeypatch):
        """Overflow regime (survivors >> capacity): payload + residual must
        still reconstruct acc exactly — truncated blocks keep ALL their
        survivors in the residual, the payload carries no garbage, and
        `count` reports what actually shipped (review r3 findings)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_compressed_dp.ops import kernels as K

        monkeypatch.setattr(K, "_PACK_ROWS", 16)
        rng = np.random.default_rng(3)
        n, keep = 8192, 128
        acc = jnp.asarray(rng.standard_normal(n), jnp.float32)
        t = jnp.asarray(0.01, jnp.float32)  # ~99% survive: massive overflow
        vals, idx, ef, count = K.pack_by_threshold(
            acc, t, keep, want_ef=True, interpret=True)
        a = np.asarray(acc)
        dense = np.zeros(n, np.float64)
        np.add.at(dense, np.asarray(idx), np.asarray(vals, np.float64))
        # payload + residual == acc for surviving coords; residual == acc
        # for non-survivors; nothing lost, nothing duplicated
        np.testing.assert_allclose(dense + np.asarray(ef, np.float64), a,
                                   rtol=1e-6, atol=1e-7)
        nz = np.asarray(vals) != 0
        assert int(count) == nz.sum()          # count == shipped survivors
        assert nz.sum() < np.count_nonzero(np.abs(a) >= 0.01)  # truncated
        assert np.all(np.asarray(idx) < n)     # no uninitialised garbage


@pytest.mark.quick
class TestSegPack:
    """Segmented shift-network pack (round 4, the r3 follow-up): per-4096-
    element-segment compaction via log-round static rolls — no per-element
    dynamic stores, no one-hot materialisation (the two measured r3 walls)."""

    def _ref(self, x, t, keep):
        import numpy as np

        n = len(x)
        m = np.abs(x) >= t
        out_v, out_i, elig_mask = [], [], np.zeros(n, bool)
        for s in range(-(-n // 4096)):
            seg = slice(s * 4096, min((s + 1) * 4096, n))
            idx = np.nonzero(m[seg])[0][:128] + s * 4096
            out_v.extend(x[idx])
            out_i.extend(idx)
            elig_mask[idx] = True
        pad = keep - len(out_v[:keep])
        sent = np.nonzero(elig_mask)[0][:keep]
        ef = x.copy()
        ef[sent] = 0.0
        return (np.concatenate([out_v[:keep], np.zeros(pad)]),
                np.concatenate([out_i[:keep], np.zeros(pad, int)]), ef)

    def _check(self, n, t, keep, seed=0):
        import numpy as np

        from tpu_compressed_dp.ops import kernels as K

        rng = np.random.default_rng(seed)
        x = rng.standard_normal(n).astype(np.float32)
        vals, idx, new_ef, elig, counts = K.seg_pack_by_threshold(
            jnp.asarray(x), jnp.float32(t), keep, interpret=True)
        pv, pi = K.seg_pack_payload(vals, idx, elig, keep)
        rv, ri, ref_ef = self._ref(x, t, keep)
        np.testing.assert_allclose(np.asarray(pv), rv, rtol=1e-6)
        assert np.array_equal(np.asarray(pi), ri)
        np.testing.assert_allclose(np.asarray(new_ef), ref_ef, rtol=1e-6)
        assert np.array_equal(np.asarray(elig),
                              np.minimum(np.asarray(counts), 128))

    def test_sparse_multi_segment(self):
        self._check(13000, 2.0, 150)

    def test_cap_overflow_spills_to_ef(self):
        # t=0.5 -> ~60% survivors, far beyond the 128/4096 cap: overflow must
        # stay in the residual and later survivors take the payload slots
        self._check(9000, 0.5, 200, seed=3)

    def test_keep_truncation_and_ragged_tail(self):
        self._check(4096 * 2 + 777, 1.5, 64, seed=5)

    def test_want_ef_off(self):
        import numpy as np

        from tpu_compressed_dp.ops import kernels as K

        rng = np.random.default_rng(7)
        x = rng.standard_normal(6000).astype(np.float32)
        vals, idx, new_ef, elig, _ = K.seg_pack_by_threshold(
            jnp.asarray(x), jnp.float32(2.0), 40, want_ef=False,
            interpret=True)
        assert new_ef is None
        pv, pi = K.seg_pack_payload(vals, idx, elig, 40)
        rv, ri, _ = self._ref(x, 2.0, 40)
        np.testing.assert_allclose(np.asarray(pv), rv, rtol=1e-6)
        assert np.array_equal(np.asarray(pi), ri)

    def test_dispatch_gate(self, monkeypatch):
        from tpu_compressed_dp.ops import kernels as K

        # OFF by default everywhere (round-4 measured tie vs the unfused
        # chain, with selection degradation on concentrated gradients)
        assert not K.use_seg_pack(1 << 20, (1 << 20) // 100)
        monkeypatch.setattr(K, "_SEG_PACK_DISPATCH", True)
        # density gate: keep/n beyond half the cap ratio -> exact global pack
        assert not K.use_seg_pack(1 << 20, (1 << 20) // 10)
        # int32 gate
        assert not K.use_seg_pack((1 << 31) + 10, 1000)
