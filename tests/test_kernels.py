"""Pallas kernel tests (interpret mode on the CPU mesh backend).

The kernels must agree with the pure-JAX reference operators in
:mod:`tpu_compressed_dp.ops.compressors`:
  * Top-K histogram threshold selects exactly the same coordinate set as the
    exact ``lax.top_k`` threshold for tie-free data;
  * the fused quantizers produce levels with the right range, sign, and
    (for QSGD) unbiasedness, from their own hardware-PRNG stream.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_compressed_dp import compat
from tpu_compressed_dp.ops import compressors, kernels


@pytest.fixture(autouse=True)
def _pallas_off_dispatch():
    # unit-test the kernels directly (interpret mode); keep auto-dispatch from
    # engaging inside compressor calls on the CPU backend
    kernels.set_pallas_mode("off")
    yield
    kernels.set_pallas_mode("auto")


class TestTopkThreshold:
    def _exact(self, mag, keep):
        return jax.lax.top_k(mag, keep)[0][-1]

    @pytest.mark.parametrize("n,keep", [(5000, 500), (8192, 1), (300, 299), (70000, 7000)])
    def test_matches_exact_selection(self, n, keep):
        mag = jnp.abs(jax.random.normal(jax.random.key(n + keep), (n,)))
        t = kernels._topk_threshold_pallas(mag, keep, interpret=True)
        exact = self._exact(mag, keep)
        # identical coordinate sets (data is tie-free at kernel resolution)
        np.testing.assert_array_equal(np.asarray(mag >= t), np.asarray(mag >= exact))
        assert int(jnp.sum(mag >= t)) == keep

    @pytest.mark.slow  # ~9 s each on the 1-core host (multi-MB interpret runs)
    @pytest.mark.parametrize("keep_frac", [0.01, 0.1])
    def test_sampled_init_large_n(self, keep_frac):
        # large n + moderate keep engages the sampled-init fast path (slab
        # subsample -> quantile-edge round -> 3 narrow rounds; the gate
        # requires the sample to be <= n/16, true here); the count >= keep
        # guarantee and tie-level surplus must hold there too
        n = 1 << 22
        keep = max(1, int(n * keep_frac))
        mag = jnp.abs(jax.random.normal(jax.random.key(7), (n,)))
        t = kernels._topk_threshold_pallas(mag, keep, interpret=True)
        cnt = int(jnp.sum(mag >= t))
        assert cnt >= keep
        assert cnt <= keep + 256  # surplus at final-bin resolution only

    def test_small_or_dense_keep_uses_exact_full_path(self):
        # mid-size tensors (sample can't be << data) must take the exact
        # full-range histogram: tie-exact count
        n = 1 << 18
        keep = 262
        mag = jnp.abs(jax.random.normal(jax.random.key(9), (n,)))
        t = kernels._topk_threshold_pallas(mag, keep, interpret=True)
        assert int(jnp.sum(mag >= t)) == keep

    @pytest.mark.slow  # ~9 s on the 1-core host
    def test_sampled_init_adversarial_layout_keeps_guarantee(self):
        # the slab sample reads the first 128 lanes of each C-block (C=4096
        # for this n/keep); hide MORE than `keep` spikes in the unsampled
        # lanes so every sample quantile is noise-level and the k-th
        # magnitude lands in the huge top bin.  The structural guarantee
        # (count >= keep; refine rounds shrink the surplus) must survive
        # this worst case — there is deliberately no data-dependent branch
        # (a cond would run both sides under shard_map).
        n = 1 << 22
        keep = int(n * 0.1)
        base = jnp.abs(jax.random.normal(jax.random.key(8), (n,))) * 1e-3
        lanes = jnp.arange(n) % 4096
        spike = lanes >= 128  # every lane the slab sample never reads
        vals = 100.0 + (jnp.arange(n) % 977).astype(jnp.float32) / 977.0
        mag = jnp.where(spike, vals, base)
        t = kernels._topk_threshold_pallas(mag, keep, interpret=True)
        cnt = int(jnp.sum(mag >= t))
        assert cnt >= keep
        # degraded-case surplus is bounded by the selected bin's population
        # after 16^3 refinement (~4% here); EF reabsorbs the boundary
        # elements the fixed-size pack then drops
        assert cnt <= int(keep * 1.05)
        assert float(t) > 1.0  # found the spikes, not the base noise

    def test_ties_all_kept(self):
        mag = jnp.ones((4096,))
        t = kernels._topk_threshold_pallas(mag, 100, interpret=True)
        assert int(jnp.sum(mag >= t)) == 4096  # reference keeps ties (core.py:183)

    def test_all_zero(self):
        mag = jnp.zeros((2048,))
        t = kernels._topk_threshold_pallas(mag, 10, interpret=True)
        assert int(jnp.sum(mag >= t)) == 2048

    def test_keep_all_shortcut(self):
        mag = jnp.abs(jax.random.normal(jax.random.key(0), (128,)))
        assert float(kernels.topk_threshold(mag, 128)) == 0.0

    def test_dispatch_cpu_is_exact(self):
        g = jax.random.normal(jax.random.key(1), (1 << 17,))
        out = compressors.top_k(g, ratio=0.01)
        keep = compressors.topk_keep_count(g.shape[0], 0.01)
        assert int(jnp.count_nonzero(out)) == keep


@pytest.mark.skipif(
    not compat.HAS_TPU_INTERPRET,
    reason="quantizer kernels draw from the TPU hardware PRNG; the stock "
           "HLO interpreter on this jax release has no prng_seed lowering")
class TestQuantKernels:
    """Interpret-mode PRNG is a zero stub on CPU (dither u == 0), so these
    cover everything EXCEPT the dither draw: with u=0 QSGD degenerates to
    deterministic truncation — range, sign, dtype, and scale stay testable.
    The dither itself (unbiasedness, per-key determinism) is validated on
    real hardware by ``test_kernels_on_tpu_chip``."""

    def test_qsgd_levels_range_sign(self):
        g = jax.random.normal(jax.random.key(2), (20000,))
        levels, scale = kernels.qsgd_quantize(g, jax.random.key(3), qstates=255,
                                              interpret=True)
        assert levels.dtype == jnp.int16
        lv = np.asarray(levels)
        assert np.all(np.abs(lv) <= 255)
        nz = lv != 0
        assert np.all(np.sign(lv[nz]) == np.sign(np.asarray(g)[nz]))
        # u=0 -> levels == floor(|g|/norm * s) exactly
        ref = np.floor(np.abs(np.asarray(g)) / np.linalg.norm(np.asarray(g)) * 255)
        np.testing.assert_array_equal(np.abs(lv), ref)
        assert float(scale) == pytest.approx(
            float(jnp.linalg.norm(g)) / 255, rel=1e-6)

    def test_terngrad_levels(self):
        g = jax.random.normal(jax.random.key(7), (12000,))
        levels, scale = kernels.terngrad_quantize(g, jax.random.key(8), interpret=True)
        assert levels.dtype == jnp.int8
        lv = np.asarray(levels)
        assert set(np.unique(lv)) <= {-1, 0, 1}
        nz = lv != 0
        assert np.all(np.sign(lv[nz]) == np.sign(np.asarray(g)[nz]))
        assert float(scale) == pytest.approx(float(jnp.max(jnp.abs(g))))

    def test_zero_grad_maps_to_zero(self):
        g = jnp.zeros((8192,))
        lq, sq = kernels.qsgd_quantize(g, jax.random.key(9), interpret=True)
        lt, st = kernels.terngrad_quantize(g, jax.random.key(9), interpret=True)
        assert not np.asarray(lq).any() and not np.asarray(lt).any()
        assert float(sq) == 0.0 and float(st) == 0.0


def _tpu_present() -> bool:
    import shutil, subprocess, sys

    code = (
        "import os, jax, sys;"
        "sys.exit(0 if jax.devices()[0].platform != 'cpu' else 1)"
    )
    env = {k: v for k, v in __import__("os").environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        return subprocess.run([sys.executable, "-c", code], env=env,
                              timeout=120, capture_output=True).returncode == 0
    except Exception:
        return False


@pytest.mark.skipif(not _tpu_present(), reason="no TPU attached")
def test_kernels_on_tpu_chip():
    """Compiled (non-interpret) kernels on the real chip: exact top-k set,
    QSGD unbiasedness + per-key determinism of the hardware-PRNG dither."""
    import os, subprocess, sys

    script = r"""
import jax, numpy as np, jax.numpy as jnp
from tpu_compressed_dp.ops import kernels
g = jax.random.normal(jax.random.key(1), (1 << 20,))
mag = jnp.abs(g); keep = 10000
t = jax.jit(lambda m: kernels._topk_threshold_pallas(m, keep))(mag)
exact = jax.lax.top_k(mag, keep)[0][-1]
assert (np.asarray(mag >= t) == np.asarray(mag >= exact)).all()
assert int((mag >= t).sum()) == keep
f = jax.jit(lambda g, k: kernels.qsgd_quantize(g, k, qstates=255))
lv, sc = f(g, jax.random.key(2))
lv = np.asarray(lv); sc = float(sc)
err = sc * lv - np.asarray(g)
assert abs(err.mean()) < 3 * sc / np.sqrt(len(g)), err.mean()
assert (np.asarray(f(g, jax.random.key(2))[0]) == lv).all()
assert not (np.asarray(f(g, jax.random.key(3))[0]) == lv).all()
print("OK")
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + (
        os.pathsep + env["PYTHONPATH"] if "PYTHONPATH" in env else "")
    res = subprocess.run([sys.executable, "-c", script], env=env, timeout=560,
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "OK" in res.stdout


class TestFusedSparsify:
    """The simulate-mode fused epilogue must match the unfused
    where/subtract/count chain exactly."""

    @pytest.mark.parametrize("want_ef", [True, False])
    def test_matches_unfused(self, want_ef):
        n = 5000
        acc = jax.random.normal(jax.random.key(1), (n,))
        t = kernels.topk_threshold(jnp.abs(acc), 500)
        comp, new_ef, cnt = kernels.fused_sparsify(acc, t, want_ef=want_ef,
                                                   interpret=True)
        exp_comp = jnp.where(jnp.abs(acc) >= t, acc, 0.0)
        np.testing.assert_allclose(np.asarray(comp), np.asarray(exp_comp),
                                   rtol=1e-6)
        if want_ef:
            np.testing.assert_allclose(np.asarray(new_ef),
                                       np.asarray(acc - exp_comp), rtol=1e-6)
        else:
            assert new_ef is None
        assert int(cnt) == int(jnp.count_nonzero(exp_comp))

    def test_zero_threshold_counts_nonzeros_only(self):
        # t == 0 keeps every real coordinate; the pad tail AND exact zeros
        # must not count as sent (count_nonzero parity with the unfused path)
        n = 200  # far from a chunk multiple
        acc = jnp.ones((n,)).at[7].set(0.0).at[100].set(0.0)
        comp, new_ef, cnt = kernels.fused_sparsify(
            acc, jnp.float32(0.0), interpret=True)
        assert int(cnt) == n - 2
        np.testing.assert_allclose(np.asarray(comp), np.asarray(acc))
        np.testing.assert_allclose(np.asarray(new_ef), np.zeros(n))


def test_topk_threshold_jnp_fallback_guarantee():
    """The pure-jnp histogram (the >int32 fallback) keeps the structural
    count(mag >= t) >= keep guarantee with tie-resolution surplus only."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_compressed_dp.ops.kernels import _topk_threshold_jnp

    for seed, n, keep in [(0, 4096, 41), (1, 1000, 1), (2, 8192, 8000)]:
        mag = jnp.abs(jax.random.normal(jax.random.key(seed), (n,)))
        t = _topk_threshold_jnp(mag, keep)
        cnt = int(jnp.sum(mag >= t))
        assert cnt >= keep
        exact = float(jax.lax.top_k(mag, keep)[0][-1])
        # threshold within the refinement resolution of the exact k-th value
        assert float(t) <= exact
        assert cnt <= keep + max(8, int(0.01 * n))


class TestPackByThreshold:
    """Fused wire-pack kernel (VERDICT r2 #4): correct but slower than the
    unfused chain on this chip — kept in-tree as a measured negative result
    (benchmarks/pack_kernel_r3.txt), NOT dispatched by the wire path."""

    def _check(self, n, keep, seed=0):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_compressed_dp.ops import kernels as K

        rng = np.random.default_rng(seed)
        acc = jnp.asarray(rng.standard_normal(n), jnp.float32)
        t = jnp.asarray(
            np.partition(np.abs(np.asarray(acc)), n - keep)[n - keep],
            jnp.float32)
        vals, idx, ef, count = K.pack_by_threshold(
            acc, t, keep, want_ef=True, interpret=True)
        mask = np.asarray(jnp.abs(acc) >= t)
        a = np.asarray(acc)
        dense = np.zeros(n, np.float64)
        np.add.at(dense, np.asarray(idx), np.asarray(vals, np.float64))
        np.testing.assert_allclose(dense, np.where(mask, a, 0.0),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(ef), np.where(mask, 0.0, a))
        assert int(count) == mask.sum()
        nz = np.asarray(vals) != 0
        assert nz.sum() == mask.sum()
        assert np.all(np.diff(np.asarray(idx)[nz]) > 0)  # ascending payload

    def test_small_pack(self, monkeypatch):
        from tpu_compressed_dp.ops import kernels as K

        monkeypatch.setattr(K, "_PACK_ROWS", 16)  # interpreter-tractable
        self._check(5000, 50)

    def test_multiblock_and_ragged(self, monkeypatch):
        from tpu_compressed_dp.ops import kernels as K

        monkeypatch.setattr(K, "_PACK_ROWS", 16)
        self._check(17000, 700)   # multi-block + ragged tail
        self._check(40000, 350)

    def test_payload_slots_accounting(self):
        from tpu_compressed_dp.ops import kernels as K

        P = K.pack_payload_slots(5_000_000, 50_000)
        blocks = -(-5_000_000 // (K._PACK_ROWS * 128))
        assert P == -(-50_000 // 128) * 128 + blocks * 128

    def test_capacity_truncation_conserves_mass(self, monkeypatch):
        """Overflow regime (survivors >> capacity): payload + residual must
        still reconstruct acc exactly — truncated blocks keep ALL their
        survivors in the residual, the payload carries no garbage, and
        `count` reports what actually shipped (review r3 findings)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_compressed_dp.ops import kernels as K

        monkeypatch.setattr(K, "_PACK_ROWS", 16)
        rng = np.random.default_rng(3)
        n, keep = 8192, 128
        acc = jnp.asarray(rng.standard_normal(n), jnp.float32)
        t = jnp.asarray(0.01, jnp.float32)  # ~99% survive: massive overflow
        vals, idx, ef, count = K.pack_by_threshold(
            acc, t, keep, want_ef=True, interpret=True)
        a = np.asarray(acc)
        dense = np.zeros(n, np.float64)
        np.add.at(dense, np.asarray(idx), np.asarray(vals, np.float64))
        # payload + residual == acc for surviving coords; residual == acc
        # for non-survivors; nothing lost, nothing duplicated
        np.testing.assert_allclose(dense + np.asarray(ef, np.float64), a,
                                   rtol=1e-6, atol=1e-7)
        nz = np.asarray(vals) != 0
        assert int(count) == nz.sum()          # count == shipped survivors
        assert nz.sum() < np.count_nonzero(np.abs(a) >= 0.01)  # truncated
        assert np.all(np.asarray(idx) < n)     # no uninitialised garbage


@pytest.mark.quick
class TestSegPack:
    """Segmented shift-network pack (round 4, the r3 follow-up): per-4096-
    element-segment compaction via log-round static rolls — no per-element
    dynamic stores, no one-hot materialisation (the two measured r3 walls)."""

    def _ref(self, x, t, keep):
        import numpy as np

        n = len(x)
        m = np.abs(x) >= t
        out_v, out_i, elig_mask = [], [], np.zeros(n, bool)
        for s in range(-(-n // 4096)):
            seg = slice(s * 4096, min((s + 1) * 4096, n))
            idx = np.nonzero(m[seg])[0][:128] + s * 4096
            out_v.extend(x[idx])
            out_i.extend(idx)
            elig_mask[idx] = True
        pad = keep - len(out_v[:keep])
        sent = np.nonzero(elig_mask)[0][:keep]
        ef = x.copy()
        ef[sent] = 0.0
        return (np.concatenate([out_v[:keep], np.zeros(pad)]),
                np.concatenate([out_i[:keep], np.zeros(pad, int)]), ef)

    def _check(self, n, t, keep, seed=0):
        import numpy as np

        from tpu_compressed_dp.ops import kernels as K

        rng = np.random.default_rng(seed)
        x = rng.standard_normal(n).astype(np.float32)
        vals, idx, new_ef, elig, counts = K.seg_pack_by_threshold(
            jnp.asarray(x), jnp.float32(t), keep, interpret=True)
        pv, pi = K.seg_pack_payload(vals, idx, elig, keep)
        rv, ri, ref_ef = self._ref(x, t, keep)
        np.testing.assert_allclose(np.asarray(pv), rv, rtol=1e-6)
        assert np.array_equal(np.asarray(pi), ri)
        np.testing.assert_allclose(np.asarray(new_ef), ref_ef, rtol=1e-6)
        assert np.array_equal(np.asarray(elig),
                              np.minimum(np.asarray(counts), 128))

    def test_sparse_multi_segment(self):
        self._check(13000, 2.0, 150)

    def test_cap_overflow_spills_to_ef(self):
        # t=0.5 -> ~60% survivors, far beyond the 128/4096 cap: overflow must
        # stay in the residual and later survivors take the payload slots
        self._check(9000, 0.5, 200, seed=3)

    def test_keep_truncation_and_ragged_tail(self):
        self._check(4096 * 2 + 777, 1.5, 64, seed=5)

    def test_want_ef_off(self):
        import numpy as np

        from tpu_compressed_dp.ops import kernels as K

        rng = np.random.default_rng(7)
        x = rng.standard_normal(6000).astype(np.float32)
        vals, idx, new_ef, elig, _ = K.seg_pack_by_threshold(
            jnp.asarray(x), jnp.float32(2.0), 40, want_ef=False,
            interpret=True)
        assert new_ef is None
        pv, pi = K.seg_pack_payload(vals, idx, elig, 40)
        rv, ri, _ = self._ref(x, 2.0, 40)
        np.testing.assert_allclose(np.asarray(pv), rv, rtol=1e-6)
        assert np.array_equal(np.asarray(pi), ri)

    def test_dispatch_gate(self, monkeypatch):
        from tpu_compressed_dp.ops import kernels as K

        # OFF by default everywhere (round-4 measured tie vs the unfused
        # chain, with selection degradation on concentrated gradients)
        assert not K.use_seg_pack(1 << 20, (1 << 20) // 100)
        monkeypatch.setattr(K, "_SEG_PACK_DISPATCH", True)
        # density gate: keep/n beyond half the cap ratio -> exact global pack
        assert not K.use_seg_pack(1 << 20, (1 << 20) // 10)
        # int32 gate
        assert not K.use_seg_pack((1 << 31) + 10, 1000)


class TestFusedSelectPack:
    """One-pass select+pack vs the XLA mask -> packed_indices_from_mask ->
    sorted-gather chain: the payloads must be BITWISE identical (values,
    indices, and survivor count) whenever the mask fills the buffer —
    exactly the regime the top-k histogram threshold guarantees."""

    def _xla(self, flat, mag, t, keep):
        from tpu_compressed_dp.ops import wire

        mask = mag >= t
        idx = wire.packed_indices_from_mask(mask, keep)
        return (wire._sorted_gather(flat, idx), idx,
                jnp.sum(mask, dtype=jnp.int32))

    # tier-1 parity core: the multi-chunk ragged case in both dtypes plus
    # the keep=1 and keep=n extremes; the full size x dtype cross rides
    # `-m slow` with the rest of the wire matrix (each row pays ~2 s of
    # interpreter compile, and tier-1 runs against a fixed wall budget)
    @pytest.mark.parametrize("n,keep,dtype", [
        (70000, 700, jnp.float32),
        (70000, 700, jnp.bfloat16),
        (65536, 1, jnp.float32),
        (4096, 4096, jnp.float32),
        pytest.param(65536, 1, jnp.bfloat16, marks=pytest.mark.slow),
        pytest.param(4096, 4096, jnp.bfloat16, marks=pytest.mark.slow),
        pytest.param(12345, 300, jnp.float32, marks=pytest.mark.slow),
        pytest.param(12345, 300, jnp.bfloat16, marks=pytest.mark.slow),
    ])
    def test_bitwise_parity_topk(self, n, keep, dtype):
        flat = jax.random.normal(jax.random.key(n + keep), (n,), dtype)
        mag = jnp.abs(flat).astype(jnp.float32)
        t = kernels.topk_threshold(mag, keep)
        fv, fi, fc = kernels.fused_select_pack(flat, t, keep, interpret=True)
        xv, xi, xc = self._xla(flat, mag, t, keep)
        assert np.array_equal(np.asarray(fi), np.asarray(xi))
        assert np.array_equal(np.asarray(fv), np.asarray(xv))
        assert int(fc) == int(xc)
        assert fv.dtype == flat.dtype

    def test_blocktopk_scores_parity(self):
        # block scores are non-negative and serve as their own magnitudes
        flat = jax.random.normal(jax.random.key(4), (40960,))
        scores = compressors.blocktopk_scores(flat, 256)
        kb = 16
        t = kernels.topk_threshold(scores, kb)
        fv, fi, fc = kernels.fused_select_pack(scores, t, kb, interpret=True)
        _, xi, xc = self._xla(scores, scores, t, kb)
        assert np.array_equal(np.asarray(fi), np.asarray(xi))
        assert int(fc) == int(xc)

    def test_monotone_invariant_on_fused_output(self):
        # full buffer -> strictly ascending unique indices: the downstream
        # sorted/unique scatter hints depend on this
        from tpu_compressed_dp.ops import wire

        flat = jax.random.normal(jax.random.key(5), (30000,))
        t = kernels.topk_threshold(jnp.abs(flat), 300)
        _, fi, _ = kernels.fused_select_pack(flat, t, 300, interpret=True)
        assert bool(wire.packed_indices_monotone(fi))

    def test_underfull_pads_zero_value_zero_index(self):
        # an underfull mask (threshold above every |x|) pads (0.0, 0) —
        # scatter-add identities, unlike the XLA chain's flat[0] replication
        flat = jnp.arange(1.0, 5001.0)
        fv, fi, fc = kernels.fused_select_pack(
            flat, jnp.float32(4998.5), 10, interpret=True)
        assert int(fc) == 2
        np.testing.assert_array_equal(
            np.asarray(fv), [4999.0, 5000.0] + [0.0] * 8)
        np.testing.assert_array_equal(np.asarray(fi), [4998, 4999] + [0] * 8)

    def test_dispatch_gate(self):
        assert not kernels.use_select_pack(1 << 10, 8)   # below size floor
        assert not kernels.use_select_pack(1 << 20, 0)   # degenerate keep
        assert not kernels.use_select_pack((1 << 31) + 2, 100)  # int32 wrap


class TestQuantPackKernels:
    """Matmul bit-packing vs the XLA shift/sum packers: wire BYTES must be
    bitwise identical (the receiver's unpack is shared)."""

    @pytest.mark.parametrize("n", [70000, 12345, 65533, 7])
    def test_pack_ternary_parity(self, n):
        from tpu_compressed_dp.ops import wire

        rng = np.random.default_rng(n)
        levels = jnp.asarray(rng.integers(-1, 2, n), jnp.int8)
        got = kernels.pack_ternary_pallas(levels, interpret=True)
        want = wire.pack_ternary(levels)
        assert np.array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("n", [70000, 12347])
    def test_qsgd_pack_levels_parity(self, n):
        from tpu_compressed_dp.ops import wire

        rng = np.random.default_rng(n)
        levels = jnp.asarray(rng.integers(-255, 256, n), jnp.int16)
        gm, gs = kernels.qsgd_pack_pallas(levels, interpret=True)
        wm, ws = wire.qsgd_wire_pack(levels, 255)
        assert np.array_equal(np.asarray(gm), np.asarray(wm))
        assert np.array_equal(np.asarray(gs), np.asarray(ws))

    @pytest.mark.skipif(
        not compat.HAS_TPU_INTERPRET,
        reason="fused quantize+pack draws from the TPU hardware PRNG; the "
               "stock HLO interpreter has no prng_seed lowering")
    def test_terngrad_pack_bytes(self):
        from tpu_compressed_dp.ops import wire

        g = jax.random.normal(jax.random.key(2), (20000,))
        packed, scale = kernels.terngrad_pack(g, jax.random.key(3),
                                              interpret=True)
        assert packed.dtype == jnp.uint8 and packed.shape == (-(-20000 // 4),)
        lv = wire.unpack_ternary(packed[None], 20000)[0]
        assert set(np.unique(np.asarray(lv))) <= {-1, 0, 1}
        assert float(scale) == pytest.approx(float(jnp.max(jnp.abs(g))))

    @pytest.mark.skipif(
        not compat.HAS_TPU_INTERPRET,
        reason="fused quantize+pack draws from the TPU hardware PRNG")
    def test_qsgd_pack_bytes(self):
        g = jax.random.normal(jax.random.key(6), (20000,))
        mags, signs, scale = kernels.qsgd_pack(g, jax.random.key(7),
                                               interpret=True)
        assert mags.dtype == jnp.uint8 and signs.dtype == jnp.uint8
        assert mags.shape == (20000,) and signs.shape == (-(-20000 // 8),)
        # u=0 stub -> levels == floor(|g|/norm * s) exactly
        ref = np.floor(np.abs(np.asarray(g))
                       / np.linalg.norm(np.asarray(g)) * 255)
        np.testing.assert_array_equal(np.asarray(mags), ref)

    def test_dispatch_gate_excludes_uninterpretable_backends(self):
        kernels.set_pallas_mode("force")
        try:
            import jax as _jax
            expected = (_jax.default_backend() == "tpu"
                        or compat.HAS_TPU_INTERPRET)
            assert kernels.use_quant_pack(1 << 20) == expected
        finally:
            kernels.set_pallas_mode("off")


class TestFusedBucketRoute:
    """Fused per-destination bucket build vs the XLA slot scatter in
    wire_sharded: buckets must be bitwise identical, monotone rows kept."""

    def _xla(self, vals, idx, valid, W, cap, shard_n):
        dest = jnp.minimum(idx // shard_n, W - 1).astype(jnp.int32)
        if valid is not None:
            dest = jnp.where(valid, dest, W)
        counts = jnp.zeros((W + 1,), jnp.int32).at[dest].add(
            1, indices_are_sorted=True, mode="promise_in_bounds")
        starts = jnp.cumsum(counts) - counts
        rank = jnp.arange(idx.shape[0], dtype=jnp.int32) - starts[dest]
        accepted = rank < cap
        if valid is not None:
            accepted = accepted & valid
        slot = jnp.where(accepted, dest * cap + rank, W * cap)
        local = (idx - dest * shard_n).astype(jnp.int32)
        bvals = jnp.zeros((W * cap + 1,), vals.dtype).at[slot].add(vals)[:-1]
        bidx = jnp.full((W * cap + 1,), shard_n, jnp.int32
                        ).at[slot].set(local)[:-1]
        return bvals.reshape(W, cap), bidx.reshape(W, cap), dest

    @pytest.mark.parametrize("seed,n,keep,W", [(0, 70000, 700, 8),
                                               (1, 30000, 333, 4)])
    def test_bitwise_parity(self, seed, n, keep, W):
        rng = np.random.default_rng(seed)
        pick = np.sort(rng.choice(n, keep, replace=False))
        idx = jnp.asarray(pick, jnp.int32)
        vals = jnp.asarray(rng.standard_normal(keep), jnp.float32)
        shard_n = -(-n // W)
        cap = max(1, int(1.25 * keep / W))
        xv, xi, dest = self._xla(vals, idx, None, W, cap, shard_n)
        fv, fi = kernels.fused_bucket_route(vals, idx, dest, W, cap,
                                            shard_n, interpret=True)
        assert np.array_equal(np.asarray(fv), np.asarray(xv))
        assert np.array_equal(np.asarray(fi), np.asarray(xi))

    def test_valid_prefix_routes_to_dump(self, ):
        # threshold-style zero-padded tails (valid prefix) must not consume
        # any bucket capacity
        rng = np.random.default_rng(2)
        n, keep, nvalid, W = 40000, 77, 60, 8
        pick = np.sort(rng.choice(n, nvalid, replace=False))
        idx = jnp.asarray(np.concatenate([pick, np.zeros(keep - nvalid)]),
                          jnp.int32)
        vals = jnp.asarray(
            np.concatenate([rng.standard_normal(nvalid),
                            np.zeros(keep - nvalid)]), jnp.float32)
        valid = jnp.arange(keep) < nvalid
        shard_n = -(-n // W)
        cap = 13
        xv, xi, dest = self._xla(vals, idx, valid, W, cap, shard_n)
        fv, fi = kernels.fused_bucket_route(vals, idx, dest, W, cap,
                                            shard_n, interpret=True)
        assert np.array_equal(np.asarray(fv), np.asarray(xv))
        assert np.array_equal(np.asarray(fi), np.asarray(xi))
        # monotone rows: filled ascending prefix then constant shard_n tail
        for w in range(W):
            row = np.asarray(fi[w])
            filled = row[row < shard_n]
            assert np.all(np.diff(filled) > 0)

    def test_dispatch_gate(self):
        assert not kernels.use_bucket_route(1 << 10, 8, 64)   # size floor
        assert not kernels.use_bucket_route(1 << 20, 1, 64)   # no routing
        assert not kernels.use_bucket_route(1 << 20, 8, 1 << 20)  # cap blowup


class TestPoisonedTailHistogram:
    """A NaN/Inf guard-vetoed gradient must not collapse the histogram bin
    edges: a non-finite ``max(mag)`` used to propagate into every edge
    (``x >= NaN`` is false everywhere), driving the survivor count to zero,
    underfilling the pack, and voiding the sorted/unique scatter hints.
    The FP32_MAX clamp keeps the structural ``count >= keep`` guarantee —
    degraded resolution (t -> 0, EF reabsorbs the surplus), never a
    duplicate-index payload.  The -1.0 padding fill stays strictly below
    every edge, so padding lanes never leak into the counts either."""

    @pytest.mark.parametrize("poison", ["nan", "inf", "both"])
    def test_pallas_histogram_guarantee_survives(self, poison):
        mag = jnp.abs(jax.random.normal(jax.random.key(11), (10000,)))
        if poison in ("nan", "both"):
            mag = mag.at[17].set(jnp.nan)
        if poison in ("inf", "both"):
            mag = mag.at[4242].set(jnp.inf)
        t = kernels._topk_threshold_pallas(mag, 100, interpret=True)
        assert bool(jnp.isfinite(t))
        assert int(jnp.sum(mag >= t)) >= 100  # NaN compares false: vetoed

    @pytest.mark.parametrize("poison", ["nan", "inf"])
    def test_jnp_fallback_guarantee_survives(self, poison):
        mag = jnp.abs(jax.random.normal(jax.random.key(12), (4096,)))
        mag = mag.at[7].set(jnp.nan if poison == "nan" else jnp.inf)
        t = kernels._topk_threshold_jnp(mag, 41)
        assert bool(jnp.isfinite(t))
        assert int(jnp.sum(mag >= t)) >= 41

    def test_exact_path_nan_demoted_below_topk(self):
        # the exact lax.top_k dispatch path: NaN sorts as LARGEST and would
        # steal a slot, landing the threshold one rank too high (underfull
        # pack).  The demotion keeps count(mag >= t) >= keep with NaN vetoed.
        mag = jnp.abs(jax.random.normal(jax.random.key(14), (70000,)))
        mag = mag.at[123].set(jnp.nan)
        t = kernels.topk_threshold(mag, 700)
        assert int(jnp.sum(mag >= t)) >= 700
        assert not bool(jnp.isnan(mag[123]) & (mag[123] >= t))

    def test_finite_inputs_unchanged(self):
        # the clamp must be invisible for ordinary finite gradients
        mag = jnp.abs(jax.random.normal(jax.random.key(13), (8192,)))
        t = kernels._topk_threshold_pallas(mag, 80, interpret=True)
        exact = jax.lax.top_k(mag, 80)[0][-1]
        np.testing.assert_array_equal(np.asarray(mag >= t),
                                      np.asarray(mag >= exact))
