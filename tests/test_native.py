"""Native image-kernel tests: PIL parity, loader-backend equivalence."""

import numpy as np
import pytest
from PIL import Image

from tpu_compressed_dp.data import imagenet as inet
from tpu_compressed_dp.data import native


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(0)
    base = rng.integers(0, 255, size=(13, 17, 3)).astype(np.uint8)
    return np.asarray(Image.fromarray(base).resize((170, 130), Image.BILINEAR),
                      np.uint8)


def test_builds_and_available():
    assert native.available()  # g++ is part of the image toolchain


@pytest.mark.parametrize("box,out,flip", [
    ((10, 20, 150, 110), (64, 64), False),     # downscale
    ((0, 0, 170, 130), (32, 48), True),        # heavy downscale + flip
    ((5.5, 7.25, 100.5, 90.75), (224, 224), False),  # fractional box, upscale
    ((0, 0, 170, 130), (130, 170), False),     # identity
])
def test_pil_parity(img, box, out, flip):
    ref = np.asarray(
        Image.fromarray(img).resize((out[1], out[0]), Image.BILINEAR, box=box),
        np.uint8)
    if flip:
        ref = ref[:, ::-1]
    got = native.crop_resize(img, box, out[0], out[1], flip)
    assert got.shape == ref.shape and got.dtype == np.uint8
    assert np.abs(got.astype(int) - ref.astype(int)).max() <= 1  # rounding only


def test_identity_exact(img):
    got = native.crop_resize(img, (0, 0, img.shape[1], img.shape[0]),
                             img.shape[0], img.shape[1])
    np.testing.assert_array_equal(got, img)


def test_bad_input_raises(img):
    with pytest.raises(ValueError, match="HWC"):
        native.crop_resize(img[..., 0], (0, 0, 8, 8), 8, 8)


class TestLoaderBackends:
    def test_train_loader_backend_parity(self):
        ds = inet.SyntheticImages(32, num_classes=8)
        a = inet.TrainLoader(ds, 8, 32, seed=5, workers=2, backend="pil")
        b = inet.TrainLoader(ds, 8, 32, seed=5, workers=2, backend="native")
        for ba, bb in zip(a, b):
            np.testing.assert_array_equal(ba["target"], bb["target"])
            diff = np.abs(ba["input"].astype(int) - bb["input"].astype(int))
            assert diff.max() <= 1  # same boxes/flips; rounding-only pixels

    def test_val_loader_backend_close(self):
        ds = inet.SyntheticImages(32, num_classes=8)
        a = inet.ValLoader(ds, 8, 32, workers=2, backend="pil")
        b = inet.ValLoader(ds, 8, 32, workers=2, backend="native")
        for ba, bb in zip(a, b):
            diff = np.abs(ba["input"].astype(int) - bb["input"].astype(int))
            assert diff.max() <= 1  # native box reproduces the two-step crop

    def test_native_requested_explicitly(self):
        ds = inet.SyntheticImages(8, num_classes=2)
        dl = inet.TrainLoader(ds, 4, 16, backend="native")
        assert dl.native
        batch = next(iter(dl))
        assert batch["input"].shape == (4, 16, 16, 3)
