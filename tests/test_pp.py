"""Pipeline-parallel step tests: schedule-invariance (the pipeline is only a
schedule — the math must equal the single-device forward), learning under
compression, and config validation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_compressed_dp import compat
from tpu_compressed_dp.models import transformer as tf
from tpu_compressed_dp.parallel.dp import CompressionConfig
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.pp_step import (
    init_pp_ef_state,
    make_pp_mesh,
    make_pp_train_step,
    stack_layer_params,
)
from tpu_compressed_dp.train.state import TrainState


def _cfg(**kw):
    base = dict(vocab_size=64, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
                ffn_hidden=64, dtype=jnp.float32)
    base.update(kw)
    return tf.LlamaConfig(**base)


def _setup(cfg, mesh, comp, lr=0.0, microbatches=2):
    params = tf.init_llama(cfg, jax.random.key(0))
    sp = stack_layer_params(params)
    opt = SGD(lr=lr, momentum=0.9 if lr else 0.0)
    state = TrainState.create(
        sp, {}, opt.init(sp), init_pp_ef_state(cfg, sp, comp, mesh),
        jax.random.key(3),
    )
    step = make_pp_train_step(cfg, opt, comp, mesh, microbatches=microbatches,
                              donate=False)
    return params, state, step


@pytest.mark.parametrize("dp,pp,mb", [
    pytest.param(1, 2, 2, marks=pytest.mark.slow),
    (2, 2, 2),   # the general dp>1 row stays tier-1
    pytest.param(1, 4, 3, marks=pytest.mark.slow),
    (2, 4, 1),
])
def test_pipeline_loss_matches_single_device(dp, pp, mb):
    cfg = _cfg()
    x = jax.random.randint(jax.random.key(1), (4 * dp * mb, 16), 0, 64)
    y = jax.random.randint(jax.random.key(2), (4 * dp * mb, 16), 0, 64)
    ref = float(tf.vocab_parallel_xent(tf.apply_llama(cfg, params := tf.init_llama(
        cfg, jax.random.key(0)), x), y))
    mesh = make_pp_mesh(dp, pp)
    _, state, step = _setup(cfg, mesh, CompressionConfig(method=None),
                            microbatches=mb)
    _, m = step(state, {"input": x, "target": y})
    assert float(m["loss"]) == pytest.approx(ref, rel=1e-5)


def test_pipeline_learns_with_compression():
    cfg = _cfg()
    mesh = make_pp_mesh(2, 2)
    comp = CompressionConfig(method="topk", granularity="entiremodel",
                             ratio=0.05, error_feedback=True)
    _, state, step = _setup(cfg, mesh, comp, lr=0.2)
    batch = {
        "input": jax.random.randint(jax.random.key(1), (8, 16), 0, 64),
        "target": jax.random.randint(jax.random.key(2), (8, 16), 0, 64),
    }
    losses = []
    for _ in range(8):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert float(m["comm/sent_elems"]) / float(m["comm/dense_elems"]) == \
        pytest.approx(0.05, rel=0.05)
    ef_norm = sum(float(jnp.sum(jnp.abs(e))) for e in jax.tree.leaves(state.ef))
    assert ef_norm > 0


def test_pipeline_clip_stabilisers():
    """clip_norm + clip_sent_norm through the pipelined step: pipe-sharded
    layer norms psum over the pipe axis; training stays finite and moves."""
    cfg = _cfg(n_layers=2)
    mesh = make_pp_mesh(2, 2)
    comp = CompressionConfig(method="randomk", granularity="entiremodel",
                             ratio=0.05, error_feedback=True, mode="wire")
    params = tf.init_llama(cfg, jax.random.key(0))
    sp = stack_layer_params(params)
    opt = SGD(lr=0.2, momentum=0.9)
    state = TrainState.create(
        sp, {}, opt.init(sp), init_pp_ef_state(cfg, sp, comp, mesh),
        jax.random.key(3),
    )
    step = make_pp_train_step(cfg, opt, comp, mesh, microbatches=2,
                              clip_norm=1.0, clip_sent_norm=1.0, donate=False)
    batch = {
        "input": jax.random.randint(jax.random.key(1), (8, 16), 0, 64),
        "target": jax.random.randint(jax.random.key(2), (8, 16), 0, 64),
    }
    losses = []
    for _ in range(6):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_pipeline_moe_layers():
    cfg = _cfg(n_experts=2, moe_every=1, capacity_factor=4.0)
    mesh = make_pp_mesh(1, 2)
    x = jax.random.randint(jax.random.key(1), (4, 16), 0, 64)
    y = jax.random.randint(jax.random.key(2), (4, 16), 0, 64)
    ref = float(tf.vocab_parallel_xent(
        tf.apply_llama(cfg, tf.init_llama(cfg, jax.random.key(0)), x), y))
    _, state, step = _setup(cfg, mesh, CompressionConfig(method=None))
    _, m = step(state, {"input": x, "target": y})
    assert float(m["loss"]) == pytest.approx(ref, rel=1e-5)


def test_validation_errors():
    cfg = _cfg(n_layers=3)
    with pytest.raises(ValueError, match="divide"):
        make_pp_train_step(cfg, SGD(lr=0.1), CompressionConfig(),
                           make_pp_mesh(1, 2), microbatches=2)
    cfg = _cfg(n_experts=2, moe_every=2)
    with pytest.raises(ValueError, match="homogeneous"):
        make_pp_train_step(cfg, SGD(lr=0.1), CompressionConfig(),
                           make_pp_mesh(1, 2), microbatches=2)
    with pytest.raises(ValueError, match="homogeneous"):
        stack_layer_params(tf.init_llama(cfg, jax.random.key(0)))


@pytest.mark.xfail(
    not compat.HAS_VMA,
    reason="old-jax layout artifact: Orbax-restored arrays compile a "
           "different executable than step outputs (bitwise-equal values "
           "and shardings verified), whose fp reduction reorder flips "
           "top-k threshold ties — ~1e-3 trajectory divergence after one "
           "step; exact on VMA-era jax",
    strict=False)
def test_pp_checkpoint_resume(tmp_path):
    """PP-step checkpoint/resume (`train_imagenet_nv.py:193-198` analog):
    save mid-run, restore into a fresh state, re-place on the (data, pipe)
    mesh, and continue stepping with identical results to the uninterrupted
    run."""
    from tpu_compressed_dp.train.pp_step import place_pp_state
    from tpu_compressed_dp.utils.checkpoint import Checkpointer

    cfg = _cfg(n_layers=2)
    mesh = make_pp_mesh(2, 2)
    comp = CompressionConfig(method="topk", granularity="entiremodel",
                             ratio=0.25, error_feedback=True)
    _, state, step = _setup(cfg, mesh, comp, lr=1e-2)
    batch = {
        "input": jax.random.randint(jax.random.key(5), (8, 16), 0, 64),
        "target": jax.random.randint(jax.random.key(6), (8, 16), 0, 64),
    }
    state, _ = step(state, batch)
    state, _ = step(state, batch)

    ckpt = Checkpointer(str(tmp_path / "pp"))
    ckpt.save(state, {"step": int(state.step)})
    ckpt.close()

    # uninterrupted continuation (reference trajectory)
    cont, m_ref = step(state, batch)

    # restore into a freshly-initialised state, re-place, continue
    _, fresh, step2 = _setup(cfg, mesh, comp, lr=1e-2)
    restore = Checkpointer(str(tmp_path / "pp"))
    restored, meta = restore.restore(fresh)
    restore.close()
    assert meta["step"] == 2
    restored = place_pp_state(restored, cfg, comp, mesh)
    assert int(restored.step) == 2
    resumed, m_new = step2(restored, batch)
    assert int(resumed.step) == 3
    assert float(m_new["loss"]) == pytest.approx(float(m_ref["loss"]), rel=1e-6)
    # EF residual survived the round-trip (it is part of the checkpoint)
    for a, b in zip(jax.tree.leaves(cont.ef), jax.tree.leaves(resumed.ef)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


@pytest.mark.parametrize("dp,pp,tp,mb",
                         [(1, 2, 2, 2), (2, 2, 2, 4), (1, 2, 4, 2),
                          # mb % pp != 0: the deferred-head uneven fallback
                          # (every stage heads the full drained batch, scale
                          # 1/stages) must still match (ADVICE r3)
                          (1, 2, 2, 3)])
def test_pipeline_tensor_composition_matches_single_device(dp, pp, tp, mb):
    """pipe x tensor (VERDICT r2 #9): megatron sharding inside each stage
    must leave the loss equal to the unsharded single-device forward."""
    cfg = _cfg(n_kv_heads=4) if tp == 4 else _cfg()
    x = jax.random.randint(jax.random.key(1), (4 * dp * mb, 16), 0, 64)
    y = jax.random.randint(jax.random.key(2), (4 * dp * mb, 16), 0, 64)
    ref = float(tf.vocab_parallel_xent(tf.apply_llama(cfg, tf.init_llama(
        cfg, jax.random.key(0)), x), y))
    mesh = make_pp_mesh(dp, pp, tp)
    _, state, step = _setup(cfg, mesh, CompressionConfig(method=None),
                            microbatches=mb)
    _, m = step(state, {"input": x, "target": y})
    assert float(m["loss"]) == pytest.approx(ref, rel=1e-5)


def test_pipeline_tensor_learns_with_compression():
    cfg = _cfg()
    mesh = make_pp_mesh(2, 2, 2)
    comp = CompressionConfig(method="topk", granularity="entiremodel",
                             ratio=0.1, error_feedback=True)
    _, state, step = _setup(cfg, mesh, comp, lr=0.3, microbatches=2)
    x = jax.random.randint(jax.random.key(4), (8, 16), 0, 64)
    y = jnp.roll(x, -1, axis=1)
    first = last = None
    for i in range(30):
        state, m = step(state, {"input": x, "target": y})
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert last < first * 0.7
    assert float(m["comm/sent_elems"]) < float(m["comm/dense_elems"]) * 0.2


@pytest.mark.parametrize("dp,sp,pp,tp,mb",
                         [(1, 2, 2, 2, 2), (2, 2, 2, 1, 2),
                          (1, 2, 2, 2, 3)])  # uneven mb % pp fallback
def test_pipeline_full_composition_matches_single_device(dp, sp, pp, tp, mb):
    """data x seq x pipe x tensor in ONE step (round 3): ring attention over
    `seq` inside each pipeline stage, megatron sharding inside each stage,
    vocab-parallel deferred head — loss must equal the unsharded
    single-device forward."""
    cfg = _cfg()
    x = jax.random.randint(jax.random.key(1), (4 * dp * mb, 16), 0, 64)
    y = jax.random.randint(jax.random.key(2), (4 * dp * mb, 16), 0, 64)
    ref = float(tf.vocab_parallel_xent(tf.apply_llama(cfg, tf.init_llama(
        cfg, jax.random.key(0)), x), y))
    mesh = make_pp_mesh(dp, pp, tp, sp)
    _, state, step = _setup(cfg, mesh, CompressionConfig(method=None),
                            microbatches=mb)
    _, m = step(state, {"input": x, "target": y})
    assert float(m["loss"]) == pytest.approx(ref, rel=1e-5)


@pytest.mark.slow  # ~8 s; the tensor-composition parity row and
# test_pipeline_tensor_learns keep dp+pp+tp quick coverage
def test_pipeline_full_composition_learns_with_compression():
    cfg = _cfg()
    mesh = make_pp_mesh(1, 2, 2, 2)
    comp = CompressionConfig(method="topk", granularity="entiremodel",
                             ratio=0.1, error_feedback=True)
    _, state, step = _setup(cfg, mesh, comp, lr=0.3, microbatches=2)
    x = jax.random.randint(jax.random.key(4), (4, 16), 0, 64)
    y = jnp.roll(x, -1, axis=1)
    first = last = None
    for i in range(30):
        state, m = step(state, {"input": x, "target": y})
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert last < first * 0.7
    assert float(m["comm/sent_elems"]) < float(m["comm/dense_elems"]) * 0.2
