"""Owner-sharded (``transport='sharded'``) and hierarchical two-level
(``transport='hierarchical'``) sparse-allreduce transports
(ops/wire_sharded.py) against the flat all_gather combine.

The contract under test: with lossless capacities the sharded route ->
owner-reduce -> return pipeline produces IDENTICAL synced gradients and EF
residuals to the allgather combine (same selections, same scatter-add sums
— fp32 summation-order tolerance only), while at the default capacity
factors its per-chip billed wire traffic for Top-K k=1% at W=8 is at most
1/3 of the allgather transport's, trending as O(k + n/W) vs O(W*k).
Clipping (route buckets or the return union) folds into the EF residual —
transmitted + residual must equal the accumulated gradient exactly — and
is surfaced via ``comm/shard_overflow``.

Unlike tests/test_wire.py (whole-module ``slow``), these stay in tier-1:
each grid point compiles ONE shard_map computing both transports, and the
matrix covers every axis (method x world size x granularity) without the
full cross-product.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from tpu_compressed_dp.compat import shard_map

from tpu_compressed_dp.ops import wire, wire_sharded
from tpu_compressed_dp.parallel.dp import (CompressionConfig,
                                           _hier_group_bits,
                                           _sharded_group_bits,
                                           make_grad_sync, wire_rides_psum,
                                           wire_transport)
from tpu_compressed_dp.utils.meters import (per_chip_traffic_bytes,
                                            per_fabric_traffic_bytes)

pytestmark = pytest.mark.quick

LOSSLESS = 1e6  # capacity factor large enough that the clamps take over
                # (cap_dest -> shard_n, so the dense return triggers): the
                # transport is then structurally incapable of clipping


def mesh_of(w):
    assert len(jax.devices()) >= w
    return Mesh(np.array(jax.devices()[:w]), ("data",))


def cfg_pair(method, gran, w, *, factors=(LOSSLESS, LOSSLESS), ef=True,
             **extra):
    base = dict(method=method, mode="wire", granularity=gran,
                error_feedback=ef, bucket_mb=0.004, **extra)
    return (CompressionConfig(**base),
            CompressionConfig(transport="sharded", shard_route_factor=factors[0],
                              shard_return_factor=factors[1], **base))


def cfg_hier(method, gran, w, pods, *, factors=(LOSSLESS, LOSSLESS), ef=True,
             **extra):
    """(allgather, hierarchical) config pair for the two-level transport."""
    base = dict(method=method, mode="wire", granularity=gran,
                error_feedback=ef, bucket_mb=0.004, **extra)
    return (CompressionConfig(**base),
            CompressionConfig(transport="hierarchical", dp_pods=pods,
                              hier_route_factor_ici=factors[0],
                              hier_route_factor_dcn=factors[1], **base))


def make_grads(w, n=2048, n2=96, seed=0):
    k = jax.random.key(seed)
    return {"a": jax.random.normal(k, (w, n), jnp.float32),
            "b": jax.random.normal(jax.random.fold_in(k, 1), (w, n2),
                                   jnp.float32)}


def run_both(mesh, cfg_ag, cfg_sh, grads, ef0=None):
    """One compile: both transports on identical inputs."""
    w = mesh.shape["data"]
    sync_ag = make_grad_sync(cfg_ag, "data")
    sync_sh = make_grad_sync(cfg_sh, "data")
    use_ef = cfg_ag.error_feedback
    if ef0 is None:
        ef0 = jax.tree.map(lambda g: jnp.zeros_like(g), grads)

    def f(g, e):
        g1 = jax.tree.map(lambda x: x[0], g)
        e1 = jax.tree.map(lambda x: x[0], e) if use_ef else ()
        o1, ef1, _, s1 = sync_ag(g1, e1, (), jax.random.key(0))
        o2, ef2, _, s2 = sync_sh(g1, e1, (), jax.random.key(0))
        return o1, o2, ef1, ef2, s1, s2

    fn = shard_map(
        f, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P(), P(), P("data") if use_ef else P(),
                   P("data") if use_ef else P(), P(), P()),
        check_vma=False)
    return fn(grads, ef0)


# Tier-1 runs the core W=8 Top-K identity (~15 s of dual-transport
# shard_map compile on the 1-core CI host; this module collects LAST, where
# a full-suite process pays 2x nominal compile time, so anything more blows
# the 870 s budget — both longer subsets were measured timing out at 99%);
# the rest of the method x {2,4,8} x {layerwise,entiremodel,bucketed}
# matrix carries `slow` and runs in the unfiltered suite.  Granularity
# grouping itself (group_concat/split) is transport-independent and
# tier-1-covered by test_dp_sync.
_QUICK = [("topk", "entiremodel", 8)]
_SLOW = (
    [(m, g, 8) for m in ("topk", "blocktopk", "thresholdv")
     for g in ("layerwise", "bucketed")]
    + [(m, "entiremodel", 8) for m in ("blocktopk", "thresholdv")]
    + [(m, "entiremodel", w) for m in ("topk", "blocktopk", "thresholdv")
       for w in (2, 4)]
)
GRID = ([pytest.param(*c, id="-".join(map(str, c))) for c in _QUICK]
        + [pytest.param(*c, id="-".join(map(str, c)),
                        marks=pytest.mark.slow) for c in _SLOW])


class TestEquivalence:
    @pytest.mark.parametrize("method,gran,w", GRID)
    def test_matches_allgather_combine(self, method, gran, w):
        extra = {"ratio": 0.05}
        if method == "blocktopk":
            extra["block_size"] = 16
        if method == "thresholdv":
            extra = {"threshold": 1.2, "wire_cap_ratio": 0.4}
        cfg_ag, cfg_sh = cfg_pair(method, gran, w, **extra)
        grads = make_grads(w)
        o1, o2, ef1, ef2, s1, s2 = run_both(mesh_of(w), cfg_ag, cfg_sh, grads)
        for k in o1:
            np.testing.assert_allclose(
                np.asarray(o1[k]), np.asarray(o2[k]), atol=1e-6,
                err_msg=f"synced grad {k} [{method}/{gran}/W={w}]")
            np.testing.assert_allclose(
                np.asarray(ef1[k]), np.asarray(ef2[k]), atol=1e-6,
                err_msg=f"EF residual {k} [{method}/{gran}/W={w}]")
        # lossless capacities: nothing may clip
        assert float(s2.get("shard_overflow", 0.0)) == 0.0
        # and the split is three-way: route on the all_to_all, the shard
        # return on the all_gather, nothing on the psum ring (no dense
        # fallback groups in this grid except blocktopk's tiny leaf)
        assert float(s2["sent_bits_alltoall"]) > 0.0
        assert float(s2["sent_bits_allgather"]) > 0.0
        assert float(s1["sent_bits_alltoall"]) == 0.0


# Hierarchical matrix: method x virtual pod shape (dp_pods x chips on the
# flat 8- or 4-device axis) x granularity.  Tier-1 proves the W=4 2x2
# Top-K identity (one dual-transport compile at the cheapest shape that
# still exercises both reduce levels, ~15 s vs ~29 s at W=8); the W=8
# shapes and the method/granularity cross ride `slow`.
_HQUICK = [("topk", "entiremodel", 4, 2)]
_HSLOW = (
    [(m, "entiremodel", w, p) for m in ("topk", "blocktopk", "thresholdv")
     for (w, p) in ((8, 2), (8, 4), (4, 2)) if (m, w, p) != ("topk", 4, 2)]
    + [("topk", g, 8, 2) for g in ("layerwise", "bucketed")]
)
HGRID = ([pytest.param(*c, id="-".join(map(str, c))) for c in _HQUICK]
         + [pytest.param(*c, id="-".join(map(str, c)),
                         marks=pytest.mark.slow) for c in _HSLOW])


class TestHierEquivalence:
    @pytest.mark.parametrize("method,gran,w,pods", HGRID)
    def test_matches_allgather_combine(self, method, gran, w, pods):
        """Lossless capacity factors: the ici-reduce -> recompress ->
        dcn-route -> return pipeline reproduces the flat all_gather
        combine's synced gradient AND EF residual (allgather == sharded is
        the grid above; equality to the same reference closes the
        allgather <-> sharded <-> hierarchical triangle)."""
        extra = {"ratio": 0.05}
        if method == "blocktopk":
            extra["block_size"] = 16
        if method == "thresholdv":
            extra = {"threshold": 1.2, "wire_cap_ratio": 0.4}
        cfg_ag, cfg_h = cfg_hier(method, gran, w, pods, **extra)
        grads = make_grads(w)
        o1, o2, ef1, ef2, s1, s2 = run_both(mesh_of(w), cfg_ag, cfg_h, grads)
        for k in o1:
            np.testing.assert_allclose(
                np.asarray(o1[k]), np.asarray(o2[k]), atol=1e-6,
                err_msg=f"synced grad {k} [{method}/{gran}/W={w}/P={pods}]")
            np.testing.assert_allclose(
                np.asarray(ef1[k]), np.asarray(ef2[k]), atol=1e-6,
                err_msg=f"EF residual {k} [{method}/{gran}/W={w}/P={pods}]")
        # lossless capacities: nothing may clip, and the billing is
        # per-fabric ONLY — hier group bits never leak into the flat
        # psum/allgather/alltoall buckets
        assert float(s2.get("shard_overflow", 0.0)) == 0.0
        assert float(s2["sent_bits_ici"]) > 0.0      # dense pod psums
        assert float(s2["sent_bits_dcn"]) > 0.0      # inter-pod exchange
        assert float(s2["sent_bits_alltoall"]) == 0.0
        assert float(s2["sent_bits_allgather"]) == 0.0
        assert float(s1["sent_bits_ici"]) == 0.0
        assert float(s1["sent_bits_dcn"]) == 0.0

    @pytest.mark.slow  # ~13 s compile; tier-1 keeps the lossless identity
    def test_forced_interpod_clipping_conserves_mass(self):
        """Tight DCN capacity on near-disjoint selections forces inter-pod
        clips; the EF refund (union clip + bucket/union slice refund) must
        keep transmitted + residual == accumulated gradient exactly, with
        the clip surfaced on shard_overflow — the same invariant as the
        flat sharded transport's comm/shard_overflow contract."""
        w, pods, n = 8, 4, 50_000
        cfg = CompressionConfig(
            method="topk", mode="wire", granularity="entiremodel",
            ratio=0.01, error_feedback=True, transport="hierarchical",
            dp_pods=pods, hier_route_factor_ici=0.5,
            hier_route_factor_dcn=0.25)
        sync = make_grad_sync(cfg, "data")
        grads = {"a": jax.random.normal(jax.random.key(3), (w, n),
                                        jnp.float32)}
        ef0 = {"a": jnp.zeros((w, n), jnp.float32)}

        def f(g, e):
            out, ef, _, st = sync({"a": g["a"][0]}, {"a": e["a"][0]}, (),
                                  jax.random.key(0))
            return out, ef, st

        out, ef, st = shard_map(
            f, mesh=mesh_of(w), in_specs=(P("data"), P("data")),
            out_specs=(P(), P("data"), P()), check_vma=False)(grads, ef0)
        assert float(st["shard_overflow"]) > 0.0
        recon = jnp.mean(grads["a"] - ef["a"].reshape(w, n), axis=0)
        np.testing.assert_allclose(np.asarray(recon), np.asarray(out["a"]),
                                   atol=1e-6)
        # measured group bits match the static analytic formula exactly
        ici_b, rt_b, ret_b = _hier_group_bits("topk", n, w, cfg)
        assert float(st["sent_bits_ici"]) == ici_b
        assert float(st["sent_bits_dcn"]) == rt_b + ret_b
        assert float(st["sent_bits_dcn_route"]) == rt_b

    def test_dcn_trend_O_k_plus_n_over_Wpods(self):
        """Static billing trend (host arithmetic only): at fixed k the flat
        sharded transport's per-chip DCN traffic grows O(k*W)-ish with the
        whole-world collectives it rides, while hierarchical DCN stays
        O(k + n/W_pods) — the inter-pod exchange sees pods participants,
        not W.  Top-K k=1%, n=1M, both 2x4 and 4x2 at W=8."""
        n, keep = 1_000_000, 10_000
        cfg = CompressionConfig(method="topk", mode="wire", ratio=0.01,
                                transport="sharded")

        def flat_dcn(w, pods):
            route, ret = wire_sharded.sharded_payload_bits(
                n, keep, w, 1, cfg.shard_route_factor,
                cfg.shard_return_factor)
            _, dcn = per_fabric_traffic_bytes(
                0.0, ret / 8, w, route / 8, pods=pods)
            return dcn * 8

        def hier_dcn(w, pods):
            ici, rt, ret = wire_sharded.hier_payload_bits(
                n, keep, w, pods, 1.25, 1.25)
            _, dcn = per_fabric_traffic_bytes(
                0.0, 0.0, w, 0.0, ici / 8, rt / 8, ret / 8, pods=pods)
            return dcn * 8

        # both W=8 shapes beat flat on per-chip DCN at the default
        # factors; the 2x4 shape (more chips per pod -> smaller slabs on
        # the inter-pod exchange) clears 3x
        assert hier_dcn(8, 2) < flat_dcn(8, 2) / 3
        assert hier_dcn(8, 4) < flat_dcn(8, 4)
        # and the advantage grows with W at fixed pod count: flat DCN
        # per-chip bits scale with W while hier's inter-pod exchange
        # doesn't see the intra-pod fan-in at all
        for pods in (2, 4):
            r8 = hier_dcn(8, pods) / flat_dcn(8, pods)
            r64 = hier_dcn(64, pods) / flat_dcn(64, pods)
            assert r64 < r8 / 3 < 0.25, (pods, r8, r64)


class TestAcceptance:
    @pytest.mark.slow  # ~28 s shard_map compile; the analytic <=1/3 bound
    # and the measured==analytic billing identity both stay tier-1 (trend
    # test below + TestEquivalence stats asserts)
    def test_topk_1pct_w8_per_chip_bits_le_third(self):
        """ISSUE 2 acceptance: Top-K k=1%, W=8 — analytic AND measured
        per-chip wire bits under transport='sharded' at the default
        capacity factors are <= 1/3 of the allgather transport's.

        The allgather side is analytic here (its measured payload is pinned
        elsewhere: k*64 bits exactly, `sent_bits = 64.0 * ...` asserts in
        test_wire.py) so tier-1 pays one shard_map compile, not two.
        """
        from tpu_compressed_dp.ops.compressors import topk_keep_count

        w, n = 8, 100_000
        cfg = CompressionConfig(
            method="topk", mode="wire", granularity="entiremodel",
            ratio=0.01, error_feedback=True, transport="sharded")
        sync = make_grad_sync(cfg, "data")
        grads = {"a": jax.random.normal(jax.random.key(1), (w, n),
                                        jnp.float32)}
        ef0 = {"a": jnp.zeros((w, n), jnp.float32)}

        def f(g, e):
            out, ef, _, st = sync({"a": g["a"][0]}, {"a": e["a"][0]}, (),
                                  jax.random.key(0))
            return out, ef, st

        o2, ef2, s2 = shard_map(
            f, mesh=mesh_of(w), in_specs=(P("data"), P("data")),
            out_specs=(P(), P("data"), P()), check_vma=False)(grads, ef0)

        keep = topk_keep_count(n, 0.01)
        ag_chip_bits = (w - 1) * keep * 64.0    # O(W*k) flat combine
        sh_chip_bits = 8 * per_chip_traffic_bytes(
            float(s2["sent_bits_psum"]) / 8,
            float(s2["sent_bits_allgather"]) / 8, w,
            float(s2["sent_bits_alltoall"]) / 8)
        assert sh_chip_bits <= ag_chip_bits / 3, (sh_chip_bits, ag_chip_bits)
        # analytic formula agrees with the measured buffers exactly
        route_b, ret_b = _sharded_group_bits("topk", n, w, cfg)
        assert float(s2["sent_bits_alltoall"]) == route_b
        assert float(s2["sent_bits_allgather"]) == ret_b
        # the tight default factors DO clip near-disjoint random selections
        # (the counter is the sizing signal) — but clipping must never lose
        # mass: transmitted + residual == gradient, exactly
        assert float(s2["shard_overflow"]) > 0.0
        recon = jnp.mean(grads["a"] - ef2["a"].reshape(w, n), axis=0)
        np.testing.assert_allclose(np.asarray(recon), np.asarray(o2["a"]),
                                   atol=1e-6)

    def test_trend_O_k_plus_n_over_W(self):
        """Static billing trend: allgather grows linearly in W at fixed k;
        sharded per-chip bits stay O(k + n/W) — flat-ish in W."""
        n, keep = 1_000_000, 10_000
        cfg = CompressionConfig(method="topk", mode="wire", ratio=0.01,
                                transport="sharded")

        def per_chip(w):
            route, ret = wire_sharded.sharded_payload_bits(
                n, keep, w, 1, cfg.shard_route_factor, cfg.shard_return_factor)
            return (w - 1) / w * route + (w - 1) * ret

        ag = lambda w: (w - 1) * keep * 64.0
        r8, r64 = per_chip(8) / ag(8), per_chip(64) / ag(64)
        assert r64 < r8 < 0.35            # advantage grows with W
        # sharded stays within a small constant of its W=8 value while
        # allgather's per-chip bits grow ~8x from W=8 to W=64
        assert per_chip(64) < 2.0 * per_chip(8)
        assert ag(64) / ag(8) == pytest.approx(9.0, rel=0.01)


class TestOverflowAndEF:
    # the acceptance test above already proves EF conservation under the
    # default factors' clipping inside tier-1; this forces the degenerate
    # one-slot caps and runs in the unfiltered suite
    @pytest.mark.slow
    def test_clipping_reported_and_ef_conserves_mass(self):
        w, n = 8, 50_000
        mesh = mesh_of(w)
        # absurdly tight caps: one slot per destination, one return slot
        cfg = CompressionConfig(
            method="topk", mode="wire", granularity="entiremodel",
            ratio=0.01, error_feedback=True, transport="sharded",
            shard_route_factor=8 / (0.01 * n), shard_return_factor=8 / (0.01 * n))
        sync = make_grad_sync(cfg, "data")
        grads = {"a": jax.random.normal(jax.random.key(2), (w, n), jnp.float32)}
        ef0 = {"a": jnp.zeros((w, n), jnp.float32)}

        def f(g, e):
            out, ef, _, st = sync({"a": g["a"][0]}, {"a": e["a"][0]}, (),
                                  jax.random.key(0))
            return out, ef, st

        out, ef, st = shard_map(
            f, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=(P(), P("data"), P()), check_vma=False)(grads, ef0)
        assert float(st["shard_overflow"]) > 0.0
        recon = jnp.mean(grads["a"] - ef["a"].reshape(w, n), axis=0)
        np.testing.assert_allclose(np.asarray(recon), np.asarray(out["a"]),
                                   atol=1e-6)


class TestClassifier:
    def test_three_way(self):
        sh = CompressionConfig(method="topk", mode="wire", transport="sharded")
        ag = CompressionConfig(method="topk", mode="wire")
        assert wire_transport("topk", 1000, sh) == "sharded"
        assert wire_transport("topk", 1000, ag) == "allgather"
        assert wire_transport("thresholdv", 1000, sh) == "sharded"
        assert wire_transport("blocktopk", 100_000, sh) == "sharded"
        # index-free quantizers and psum riders are unaffected by transport
        assert wire_transport("terngrad", 1000, sh) == "allgather"
        assert wire_transport("qsgd", 1000, sh) == "allgather"
        assert wire_transport("none", 1000, sh) == "psum"
        assert wire_transport("powersgd", 1000, sh) == "psum"
        rk = CompressionConfig(method="randomk", mode="wire",
                               transport="sharded")
        assert wire_transport("randomk", 1000, rk) == "psum"
        # keep-all blocktopk groups psum dense regardless of transport
        tiny = CompressionConfig(method="blocktopk", mode="wire",
                                 transport="sharded", block_size=256)
        assert wire_transport("blocktopk", 100, tiny) == "psum"
        assert wire_rides_psum("blocktopk", 100, tiny)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="transport"):
            CompressionConfig(method="topk", transport="ring")
        with pytest.raises(ValueError, match="shard_route_factor"):
            CompressionConfig(method="topk", shard_route_factor=0.0)


class TestShardPlan:
    def test_caps_clamped_and_dense_return_trigger(self):
        # lossless factors: cap_dest clamps to shard_n, which makes the
        # sparse return >= the dense shard -> dense_return
        p = wire_sharded.make_shard_plan(1000, 100, 8, 1, LOSSLESS, LOSSLESS)
        assert p.shard_n == 125 and p.cap_dest == 100  # min(shard_n, keep)
        assert p.dense_return
        # tight factors on a big sparse group: sparse return wins
        p2 = wire_sharded.make_shard_plan(1_000_000, 10_000, 8, 1, 1.25, 1.25)
        assert p2.cap_dest == 1563 and p2.cap_ret == 1563
        assert not p2.dense_return
        # cap_ret never exceeds what the route can deliver
        p3 = wire_sharded.make_shard_plan(1_000_000, 10_000, 8, 1, 0.5, 100.0)
        assert p3.cap_ret <= 8 * p3.cap_dest

    def test_payload_bits_match_plan(self):
        route, ret = wire_sharded.sharded_payload_bits(
            1_000_000, 10_000, 8, 1, 1.25, 1.25)
        p = wire_sharded.make_shard_plan(1_000_000, 10_000, 8, 1, 1.25, 1.25)
        assert route == p.world * p.cap_dest * 64
        assert ret == p.cap_ret * 64


class TestRemeshPartition:
    """Elastic W -> W-1 remesh (train/elastic.py) rebuilds the step over
    the surviving mesh, which rebuilds the shard plans — the recomputed
    owner partition must re-tile the flat unit space exactly."""

    @pytest.mark.parametrize("n_units", [1, 3, 7, 10, 64, 1000])
    def test_w4_to_w3_partition_covers_exactly(self, n_units):
        # host-side arithmetic only: every unit owned exactly once at the
        # old AND the new world; bounds concatenate to [0, n_units)
        for world in (4, 3):
            plan = wire_sharded.make_shard_plan(
                n_units, max(n_units // 4, 1), world, 1, LOSSLESS, LOSSLESS)
            bounds = wire_sharded.owner_bounds(plan)
            assert len(bounds) == world
            assert bounds[0][0] == 0 and bounds[-1][1] == n_units
            for (_, hi), (lo, _) in zip(bounds, bounds[1:]):
                assert hi == lo, "gap or overlap between owner shards"
            owners = [wire_sharded.owner_of_unit(u, plan)
                      for u in range(n_units)]
            for u, o in enumerate(owners):
                lo, hi = bounds[o]
                assert lo <= u < hi, "owner_of_unit disagrees with bounds"
            # ownership is a partition: each unit in exactly one range
            assert sum(hi - lo for lo, hi in bounds) == n_units

    def test_owner_of_unit_rejects_out_of_range(self):
        plan = wire_sharded.make_shard_plan(10, 4, 4, 1, LOSSLESS, LOSSLESS)
        with pytest.raises(ValueError):
            wire_sharded.owner_of_unit(10, plan)
        with pytest.raises(ValueError):
            wire_sharded.owner_of_unit(-1, plan)

    def test_shard_boundaries_shift_on_remesh(self):
        # the partition is a FUNCTION of W: after 4 -> 3 the boundaries
        # move (shard_n grows), i.e. the rebuilt step really re-partitions
        p4 = wire_sharded.make_shard_plan(1000, 100, 4, 1, LOSSLESS, LOSSLESS)
        p3 = wire_sharded.make_shard_plan(1000, 100, 3, 1, LOSSLESS, LOSSLESS)
        assert p4.shard_n == 250 and p3.shard_n == 334
        assert wire_sharded.owner_bounds(p4) != wire_sharded.owner_bounds(p3)

    @pytest.mark.slow  # ~14 s dual compile; tier-1 covers the remesh path
    def test_equivalence_at_surviving_world(self):
        """allgather <-> sharded equivalence holds at the post-remesh W=3
        (smaller grads than the main grid to keep the dual compile cheap);
        the quick tier keeps the host-side partition coverage above plus
        the chaos drill's wire+sharded remesh row — this dual-transport
        compile and the full W cross below ride the slow tier."""
        w = 3
        cfg_ag, cfg_sh = cfg_pair("topk", "entiremodel", w, ratio=0.05)
        grads = make_grads(w, n=512, n2=48)
        o1, o2, ef1, ef2, _, s2 = run_both(mesh_of(w), cfg_ag, cfg_sh, grads)
        for k in o1:
            np.testing.assert_allclose(np.asarray(o1[k]), np.asarray(o2[k]),
                                       atol=1e-6, err_msg=f"synced {k} @W=3")
            np.testing.assert_allclose(np.asarray(ef1[k]), np.asarray(ef2[k]),
                                       atol=1e-6, err_msg=f"EF {k} @W=3")
        assert float(s2.get("shard_overflow", 0.0)) == 0.0

    @pytest.mark.slow
    @pytest.mark.parametrize("w", [7, 5, 3, 2])
    def test_equivalence_full_surviving_worlds(self, w):
        """The full cross of surviving world sizes a W=8 job can remesh
        down through — the owner partition recomputes at each W and the
        transports stay equivalent."""
        cfg_ag, cfg_sh = cfg_pair("topk", "entiremodel", w, ratio=0.05)
        grads = make_grads(w)
        o1, o2, ef1, ef2, _, s2 = run_both(mesh_of(w), cfg_ag, cfg_sh, grads)
        for k in o1:
            np.testing.assert_allclose(np.asarray(o1[k]), np.asarray(o2[k]),
                                       atol=1e-6, err_msg=f"synced {k} @W={w}")
            np.testing.assert_allclose(np.asarray(ef1[k]), np.asarray(ef2[k]),
                                       atol=1e-6, err_msg=f"EF {k} @W={w}")
        assert float(s2.get("shard_overflow", 0.0)) == 0.0


class TestSimulateCounterfactual:
    def test_simulate_bills_sharded_buckets(self, mesh8):
        """mode='simulate' + transport='sharded': the psum stays dense (the
        paper protocol) but the billing is the sharded wire form's — same
        static buffer arithmetic as the wire engine's measured bits."""
        w, n = 8, 10_000
        cfg = CompressionConfig(method="topk", mode="simulate",
                                granularity="entiremodel", ratio=0.01,
                                transport="sharded", shared_mask=False)
        sync = make_grad_sync(cfg, "data")
        grads = {"a": jax.random.normal(jax.random.key(0), (w, n), jnp.float32)}

        def f(g):
            out, _, _, st = sync({"a": g["a"][0]}, (), (), jax.random.key(0))
            return out, st

        out, st = shard_map(f, mesh=mesh8, in_specs=(P("data"),),
                            out_specs=(P(), P()), check_vma=False)(grads)
        route_b, ret_b = _sharded_group_bits("topk", n, w, cfg)
        assert float(st["sent_bits_alltoall"]) == route_b
        assert float(st["sent_bits_allgather"]) == ret_b
        assert float(st["sent_bits"]) == route_b + ret_b


def test_packed_indices_monotone_debug_predicate():
    """ADVICE r5: the sorted/unique scatter hints downstream of
    packed_indices_from_mask hold only for FINITE gradients.  The debug
    predicate must certify the invariant on finite input and expose its
    violation under NaN pollution (NaN >= t is False, the mask underfills,
    trailing ranks pad with duplicate index 0)."""
    from tpu_compressed_dp.ops import kernels

    n, keep = 4096, 64
    g = jax.random.normal(jax.random.key(0), (n,), jnp.float32)
    mag = jnp.abs(g)
    t = kernels.topk_threshold(mag, keep)
    idx = wire.packed_indices_from_mask(mag >= t, keep)
    assert bool(wire.packed_indices_monotone(idx))

    g_nan = g.at[jnp.argsort(-mag)[: keep // 2]].set(jnp.nan)  # kill top half
    mag_nan = jnp.abs(g_nan)
    t_nan = kernels.topk_threshold(mag_nan, keep)
    mask = mag_nan >= t_nan
    # NaN slots compare False: the mask can underfill `keep`...
    if int(jnp.sum(mask)) < keep:
        idx_nan = wire.packed_indices_from_mask(mask, keep)
        # ...and the packed indices then violate the hinted invariant —
        # the documented precondition, not a benign degradation
        assert not bool(wire.packed_indices_monotone(idx_nan))
