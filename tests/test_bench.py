"""Benchmark-kit tests on the virtual CPU mesh: record schema and comm
accounting coherence (the analytic numbers the sweep reports must agree with
the step's own comm metrics)."""

import pytest

from tpu_compressed_dp.bench import sweep


def test_run_point_dense(mesh8):
    rec = sweep.run_point(model="resnet9", method=None, batch_size=64,
                          steps=2, warmup=1, devices=8, channels_scale=0.125)
    assert rec["devices"] == 8
    assert rec["images_per_sec"] > 0
    assert rec["sent_frac"] == 1.0 and rec["wire_frac"] == 1.0
    assert rec["payload_mb_per_step"] == rec["dense_mb_per_step"]


def test_run_point_topk_layerwise(mesh8):
    rec = sweep.run_point(model="resnet9", method="topk", ratio=0.01,
                          granularity="layerwise", batch_size=64,
                          steps=2, warmup=1, devices=8, channels_scale=0.125)
    assert 0.005 < rec["sent_frac"] < 0.05  # ~1% + tiny-tensor rounding
    assert rec["payload_mb_per_step"] < rec["dense_mb_per_step"] * 0.05
    assert rec["num_collectives"] > 1
    # topk's wire form all_gathers worker-distinct payloads: per-chip link
    # traffic is (W-1) x payload (VERDICT r2 #2), not the ring 2(W-1)/W
    assert rec["transport"] == "all_gather"
    steps_per_sec = 1e3 / rec["step_ms"]
    expect = 7 * rec["payload_mb_per_step"] / 1e3 * steps_per_sec
    assert abs(rec["allreduce_gbps_per_chip"] - expect) < max(0.05 * expect, 0.01)


def test_run_point_projected_comm_columns(mesh8):
    """VERDICT r1 weak #6: single-chip sweeps must still report the analytic
    W-chip projection so 'allreduce GB/s vs k' has numbers — with the
    method-aware transport factor (VERDICT r2 #2)."""
    rec = sweep.run_point(model="resnet9", method="topk", ratio=0.01,
                          granularity="entiremodel", batch_size=64,
                          steps=2, warmup=1, devices=8, project_devices=32,
                          channels_scale=0.125)
    steps_per_sec = 1e3 / rec["step_ms"]
    expect = 31 * rec["payload_mb_per_step"] / 1e3 * steps_per_sec
    assert rec["projected_devices"] == 32.0
    assert rec["projected_allreduce_gbps_per_chip"] > 0
    assert abs(rec["projected_allreduce_gbps_per_chip"] - expect) <= max(
        0.05 * expect, 0.01)
    assert (rec["projected_dense_allreduce_gbps_per_chip"]
            > rec["projected_allreduce_gbps_per_chip"])


@pytest.mark.slow  # ~11 s; run_point rows keep the projection columns quick
def test_projection_method_aware_topk_vs_randomk(mesh8):
    """VERDICT r2 #2 done-criterion: at W>2 and equal ratio, topk (all_gather,
    64 bits/elem) must project strictly more per-chip traffic than shared-seed
    randomk (packed ring psum, 32 bits/elem) — before this fix both were
    billed the ring factor and differed only by the index bits."""
    common = dict(model="resnet9", granularity="entiremodel", mode="wire",
                  ratio=0.01, batch_size=64, steps=2, warmup=1, devices=8,
                  project_devices=32, channels_scale=0.125)
    rec_t = sweep.run_point(method="topk", **common)
    rec_r = sweep.run_point(method="randomk", **common)
    assert rec_t["transport"] == "all_gather"
    assert rec_r["transport"] == "psum"
    # same keep count, 2x wire width, (W-1) vs 2(W-1)/W factor: ~32x at W=32
    ratio = (rec_t["projected_allreduce_gbps_per_chip"]
             / rec_r["projected_allreduce_gbps_per_chip"])
    # normalise out the measured step-rate difference between the two runs
    ratio *= rec_t["step_ms"] / rec_r["step_ms"]
    assert 25.0 < ratio < 40.0


def test_run_point_phase_breakdown(mesh8):
    """--phase_breakdown: topk wire rows carry per-phase ms columns from
    the stage ladders (obs/trace.py taxonomy) plus the live pallas_mode
    column; non-topk rows carry none (the ladder is the topk wire chain).
    Sixteenth-scale model: the assertions are schema, not timings."""
    common = dict(model="resnet9", granularity="entiremodel", mode="wire",
                  ratio=0.01, batch_size=64, steps=2, warmup=1, devices=8,
                  channels_scale=0.0625, phase_breakdown=True)
    rec = sweep.run_point(method="topk", **common)
    for k in ("phase_compress_ms", "phase_reduce_ms", "phase_ef_ms",
              "phase_update_ms"):
        assert k in rec and rec[k] >= 0.0
    assert rec["phase_compress_ms"] > 0.0
    assert rec["pallas_mode"] in ("auto", "off", "force")


@pytest.mark.slow
def test_run_point_phase_breakdown_skips_non_topk(mesh8):
    rec_q = sweep.run_point(
        method="terngrad", model="resnet9", granularity="entiremodel",
        mode="wire", ratio=0.01, batch_size=64, steps=2, warmup=1,
        devices=8, channels_scale=0.0625, phase_breakdown=True)
    assert not any(k.startswith("phase_") for k in rec_q)


def test_run_adaptive_point_schema_and_convergence(mesh8):
    """BENCH_r09 protocol: the closed-loop record carries the per-window
    trajectory + per-rung static baselines, and with a budget only the
    bottom rung satisfies the controller must walk down to it."""
    rec = sweep.run_adaptive_point(
        method="topk", granularity="entiremodel", ratio=0.5,
        rungs=(0.5, 0.25), batch_size=64, channels_scale=0.125,
        windows=3, window=1, budget_ms=20.0, bw_mbps=100.0, devices=8)
    assert rec["adaptive"] is True and rec["knob"] == "ratio"
    assert rec["rungs"] == [0.5, 0.25]
    assert len(rec["window_trace"]) == 3
    assert len(rec["static_rungs"]) == 2
    # entiremodel topk @ half-width resnet9: rung 0 bills ~33 ms of modeled
    # comm at 100 MB/s, rung 1 ~17 ms — only rung 1 fits a 20 ms budget
    assert [s["fits_budget"] for s in rec["static_rungs"]] == [False, True]
    assert rec["best_static"] == {"rung": 1, "value": 0.25}
    assert [t["rung"] for t in rec["window_trace"]] == [0, 1, 1]
    assert rec["window_trace"][0]["direction"] == "down"
    assert rec["converged_to_best_static"] is True
    assert rec["decisions"] == 3
    # descent billed more than the best-static oracle, but less than rung 0
    assert (rec["best_static_billed_bits"] < rec["adaptive_billed_bits"]
            < rec["static_rungs"][0]["bits_per_update"] * rec["updates"])


@pytest.mark.slow  # ~11 s; run_adaptive_point schema row keeps adaptive-sweep quick coverage
def test_run_sweep_adaptive_cli(mesh8, capsys):
    args = sweep.build_parser().parse_args([
        "--model", "resnet9", "--methods", "topk,terngrad",
        "--ratios", "0.5", "--granularities", "entiremodel",
        "--batch_size", "64", "--devices", "8", "--channels_scale", "0.125",
        "--adaptive", "--adaptive_windows", "2", "--adaptive_window", "1",
        "--adaptive_rungs", "0.5,0.25", "--adaptive_budget_ms", "20.0",
    ])
    records = sweep.run_sweep(args)
    # terngrad has no ladder knob -> skipped with a stderr note, no crash
    assert [r["method"] for r in records] == ["topk"]
    assert records[0]["window"] == 1 and records[0]["windows"] == 2
    assert len(records[0]["window_trace"]) == 2


def test_run_sweep_cli(mesh8, tmp_path, capsys):
    args = sweep.build_parser().parse_args([
        "--model", "resnet9", "--methods", "terngrad", "--ratios", "0.01",
        "--granularities", "entiremodel", "--batch_size", "64",
        "--steps", "2", "--warmup", "1", "--devices", "8",
        "--channels_scale", "0.125",
        "--tsv", str(tmp_path / "s.tsv"),
    ])
    records = sweep.run_sweep(args)
    # dense baseline + one terngrad point
    assert [r["method"] for r in records] == ["none", "terngrad"]
    assert records[1]["wire_frac"] < 0.1  # 2-bit levels
    lines = (tmp_path / "s.tsv").read_text().splitlines()
    comments = [ln for ln in lines if ln.startswith("#")]
    assert comments, "TSV should carry the counterfactual-column caveat header"
    assert any("COUNTERFACTUAL" in ln for ln in comments)
    assert len(lines) - len(comments) == 3  # header + dense + terngrad
