"""MoE / expert-parallel tests.

Key properties: a single-expert MoE with ample capacity IS the dense SwiGLU
(routing multiplies by softmax prob == 1); expert-parallel sharding over the
tensor axis computes the same function as the unsharded layer; over-capacity
tokens fall through to the residual; the full MoE LM step trains under
gradient compression.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from tpu_compressed_dp.compat import shard_map

from tpu_compressed_dp.models import transformer as tf


def _cfg(**kw):
    base = dict(vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
                ffn_hidden=64, dtype=jnp.float32, n_experts=4, moe_every=1,
                capacity_factor=2.0)
    base.update(kw)
    return tf.LlamaConfig(**base)


class TestMoEFFN:
    def test_single_expert_equals_dense_swiglu(self):
        cfg = _cfg(n_experts=1, capacity_factor=2.0)
        lp = {
            "router": jnp.zeros((32, 1)),
            "w_gate": jax.random.normal(jax.random.key(0), (1, 32, 64)) * 0.1,
            "w_up": jax.random.normal(jax.random.key(1), (1, 32, 64)) * 0.1,
            "w_down": jax.random.normal(jax.random.key(2), (1, 64, 32)) * 0.1,
        }
        x = jax.random.normal(jax.random.key(3), (2, 8, 32))
        out, aux = tf._moe_ffn(cfg, lp, x, None)
        gate = jax.nn.silu(x @ lp["w_gate"][0])
        dense = (gate * (x @ lp["w_up"][0])) @ lp["w_down"][0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=1e-5, rtol=1e-5)
        assert float(aux) == pytest.approx(1.0)  # perfectly balanced: E*1*1/E

    def test_capacity_drops_tokens(self):
        # capacity ~0 -> every token dropped -> output is exactly zero
        cfg = _cfg(n_experts=4, capacity_factor=1e-9)
        lp = {
            "router": jax.random.normal(jax.random.key(0), (32, 4)),
            "w_gate": jnp.ones((4, 32, 64)), "w_up": jnp.ones((4, 32, 64)),
            "w_down": jnp.ones((4, 64, 32)),
        }
        x = jax.random.normal(jax.random.key(1), (2, 8, 32))
        out, _ = tf._moe_ffn(cfg, lp, x, None)
        # capacity clamps to 1 slot per expert: at most 4 tokens survive
        nonzero_tokens = int(jnp.sum(jnp.any(out.reshape(-1, 32) != 0, axis=-1)))
        assert nonzero_tokens <= 4

    @pytest.mark.slow  # ~18 s; MoE keeps quick rows (step+compression, lm flag)
    def test_sharded_matches_unsharded(self):
        # capacity queues are per (data, seq) shard — parity with the
        # unsharded run holds exactly only in the drop-free regime, so use a
        # capacity factor >= n_experts (cap >= tokens => nothing ever drops)
        cfg = _cfg(n_experts=4, capacity_factor=8.0)
        params = tf.init_llama(cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, 64)
        ref = tf.apply_llama(cfg, params, tokens)
        from tpu_compressed_dp.train.lm_step import make_lm_mesh

        mesh = make_lm_mesh(2, 2, 2)
        got = shard_map(
            lambda p, t: tf.apply_llama(cfg, p, t, tensor_axis="tensor",
                                        seq_axis="seq"),
            mesh=mesh,
            in_specs=(tf.param_specs(cfg), P("data", "seq")),
            out_specs=P("data", "seq", "tensor"),
        )(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_aux_loss_favors_balance(self):
        cfg = _cfg(n_experts=4)
        x = jax.random.normal(jax.random.key(2), (2, 32, 32))
        # collapsed router (all tokens -> expert 0) must score worse than a
        # spread router
        collapsed = {
            "router": jnp.zeros((32, 4)).at[:, 0].set(5.0),
            "w_gate": jnp.zeros((4, 32, 64)), "w_up": jnp.zeros((4, 32, 64)),
            "w_down": jnp.zeros((4, 64, 32)),
        }
        spread = dict(collapsed, router=jnp.zeros((32, 4)))
        _, aux_c = tf._moe_ffn(cfg, collapsed, x, None)
        _, aux_s = tf._moe_ffn(cfg, spread, x, None)
        assert float(aux_c) > float(aux_s) >= 0.99

    def test_expert_divisibility_validated(self):
        with pytest.raises(ValueError, match="n_experts"):
            _cfg(n_experts=3).validate_mesh(2)


class TestMoELMStep:
    def test_moe_step_with_compression(self):
        from tpu_compressed_dp.parallel.dp import CompressionConfig
        from tpu_compressed_dp.train.lm_step import (
            init_lm_ef_state, make_lm_mesh, make_lm_train_step,
        )
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState

        cfg = _cfg(n_experts=4, moe_every=2)  # layer 1 MoE, layer 0 dense
        mesh = make_lm_mesh(2, 2, 2)
        params = tf.init_llama(cfg, jax.random.key(0))
        assert "router" in params["layers"][1] and "router" not in params["layers"][0]
        opt = SGD(lr=0.1, momentum=0.9)
        comp = CompressionConfig(method="topk", granularity="entiremodel",
                                 ratio=0.05, error_feedback=True)
        state = TrainState.create(
            params, {}, opt.init(params),
            init_lm_ef_state(cfg, params, comp, mesh), jax.random.key(1),
        )
        step = make_lm_train_step(cfg, opt, comp, mesh)
        batch = {
            "input": jax.random.randint(jax.random.key(2), (4, 16), 0, 64),
            "target": jax.random.randint(jax.random.key(3), (4, 16), 0, 64),
        }
        losses = []
        for _ in range(6):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        assert float(m["comm/sent_elems"]) / float(m["comm/dense_elems"]) == \
            pytest.approx(0.05, rel=0.05)

    def test_lm_harness_moe_flag(self):
        from tpu_compressed_dp.harness import lm

        s = lm.main(["--preset", "tiny", "--dp", "2", "--sp", "2", "--tp", "2",
                     "--experts", "4", "--moe_every", "1",
                     "--steps", "10", "--seq_len", "32", "--global_batch", "8",
                     "--fp32", "--log_every", "5"])
        assert np.isfinite(s["loss"])
