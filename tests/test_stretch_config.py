"""Shape-validate the llama3_8b stretch config (VERDICT r2 #9): the 8B
preset must wire through the (data, seq, tensor) train step — abstractly,
via jax.eval_shape, so no 32 GB of parameters ever materialise."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.models import transformer as tf
from tpu_compressed_dp.parallel.dp import CompressionConfig
from tpu_compressed_dp.train.lm_step import (
    init_lm_ef_state,
    make_lm_mesh,
    make_lm_train_step,
)
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.state import TrainState


def test_llama3_8b_wires_through_lm_step(mesh8):
    cfg = tf.llama3_8b()
    mesh = make_lm_mesh(2, 2, 2)
    comp = CompressionConfig(method="topk", granularity="entiremodel",
                             ratio=0.01, error_feedback=False)
    opt = SGD(lr=1e-3, momentum=0.9)
    step = make_lm_train_step(cfg, opt, comp, mesh, donate=False)

    params = jax.eval_shape(lambda k: tf.init_llama(cfg, k), jax.random.key(0))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert 7.5e9 < n_params < 8.5e9  # it IS the 8B config

    def make_state(key):
        p = tf.init_llama(cfg, key)
        return TrainState.create(
            p, {}, opt.init(p), init_lm_ef_state(cfg, p, comp, mesh), key)

    state = jax.eval_shape(make_state, jax.random.key(0))
    batch = {
        "input": jax.ShapeDtypeStruct((8, 1024), jnp.int32),
        "target": jax.ShapeDtypeStruct((8, 1024), jnp.int32),
    }
    out_state, metrics = jax.eval_shape(step, state, batch)
    assert metrics["loss"].shape == ()
    # parameter shapes survive the round trip
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(out_state.params)):
        assert a.shape == b.shape and a.dtype == b.dtype
    # the compressed payload accounting scales: 1% of 8B
    assert metrics["comm/sent_elems"].dtype == jnp.float32
