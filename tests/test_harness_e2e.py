"""End-to-end smoke tests of the dawn harness (`--short-epoch` analog,
SURVEY.md §4): synthetic data, few epochs, assert learning happens."""

import numpy as np
import pytest

from tpu_compressed_dp.harness import dawn


def run_dawn(tmp_path, **overrides):
    # narrow net + tiny synthetic set: CPU-mesh smoke budget (the real
    # protocol runs on TPU via this same code path)
    argv = ["--synthetic", "--synthetic_n", "512", "--channels_scale", "0.125",
            "--log_dir", str(tmp_path), "--batch_size", "64", "--devices", "8"]
    for k, v in overrides.items():
        argv += [f"--{k}"] + ([] if v is True else [str(v)])
    args = dawn.build_parser().parse_args(argv)
    return dawn.run(args)


def test_dense_resnet9_learns(tmp_path, mesh8):
    summary = run_dawn(tmp_path, epochs=3, momentum=0.9)
    assert summary["epoch"] == 3
    assert summary["train acc"] > 0.5  # synthetic blobs are easy; chance = 0.1
    assert (tmp_path / "logs.tsv").exists()
    tsv = (tmp_path / "logs.tsv").read_text().splitlines()
    assert tsv[0] == "epoch\thours\ttop1Accuracy"
    assert len(tsv) == 4


def test_compressed_topk_layerwise_learns(tmp_path, mesh8):
    summary = run_dawn(
        tmp_path, epochs=3, compress="layerwise", method="Topk", ratio=0.1,
        error_feedback=True, momentum=0.9,
    )
    assert summary["train acc"] > 0.5
    assert 0.0 < summary["sent frac"] < 0.2  # ~10% of elements sent


def test_compressed_entiremodel_qsgd(tmp_path, mesh8):
    summary = run_dawn(
        tmp_path, epochs=2, compress="entiremodel", method="RandomDithering", qstates=255,
        momentum=0.9,
    )
    assert summary["train acc"] > 0.3


def test_powersgd_layerwise_learns(tmp_path, mesh8):
    """The stateful compressor end-to-end through the quickstart ResNet-9
    path: warm-started rank-2 factors + EF residual still learn the
    synthetic task, at ~3% of the dense wire volume — all of it psum.
    5 epochs, not 3: the EF residual re-injects what the rank-2 projection
    drops, so the first epochs lag dense before the warm start locks onto
    the gradient subspace (0.12 -> 0.69 train acc across epochs 1..5)."""
    summary = run_dawn(
        tmp_path, epochs=5, compress="layerwise", method="powersgd", rank=2,
        error_feedback=True, momentum=0.9,
    )
    assert summary["train acc"] > 0.5
    assert 0.0 < summary["sent frac"] < 0.2  # r*(m+n/m) of each group


def test_epochs_rule():
    assert dawn.default_epochs("Randomk") == 40
    assert dawn.default_epochs("Thresholdv") == 40
    assert dawn.default_epochs("Topk") == 24
    assert dawn.default_epochs("none") == 24


def test_batch_size_must_divide_mesh(tmp_path):
    with pytest.raises(ValueError, match="divisible"):
        run_dawn(tmp_path, epochs=1, batch_size=100)


def test_real_data_missing_gives_clear_error(tmp_path):
    argv = ["--data_dir", str(tmp_path / "nope"), "--epochs", "1"]
    args = dawn.build_parser().parse_args(argv)
    with pytest.raises(FileNotFoundError, match="synthetic_cifar10"):
        dawn.run(args)


def test_bf16_dtype_learns_and_keeps_fp32_masters(tmp_path, mesh8):
    """--dtype bfloat16 (VERDICT r3 #5): bf16 compute must still learn on the
    synthetic blobs, and the param masters must stay fp32 (flax dtype policy
    — the reference's fp16util.py kept fp32 masters the same way)."""
    import jax
    import jax.numpy as jnp

    from tpu_compressed_dp.harness.dawn import MODELS
    from tpu_compressed_dp.models.common import init_model

    summary = run_dawn(tmp_path, epochs=3, momentum=0.9, dtype="bfloat16")
    assert summary["train acc"] > 0.5

    module = MODELS["resnet9"](0.125, dtype=jnp.bfloat16)
    params, _ = init_model(module, jax.random.key(0),
                           jnp.zeros((1, 32, 32, 3), jnp.float32))
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(params))


def test_dtype_refused_on_models_without_the_knob(tmp_path):
    import pytest as _pytest

    with _pytest.raises(ValueError, match="does not support --dtype"):
        run_dawn(tmp_path, epochs=1, network="vgg16", channels_scale=1.0,
                 dtype="bfloat16", batch_size=8, synthetic_n=64)


@pytest.mark.quick
def test_warmup_ratio_schedule_shared_source():
    """The DGC sparsity warm-up schedule is a single module-level function
    (harness applies it; tools/time_to_accuracy.py integrates it)."""
    from tpu_compressed_dp.harness.dawn import warmup_ratio_for_epoch

    seq = [warmup_ratio_for_epoch(e, ratio=0.01, warmup_epochs=16,
                                  method="randomk") for e in range(18)]
    assert seq[15] == seq[16] == seq[17] == 0.01   # reaches target, stays
    assert all(a >= b for a, b in zip(seq, seq[1:]))  # monotone decay
    assert seq[0] > 0.5 * 0.01 ** (1 / 16)         # starts near dense
    # quantizers and dense never warm up
    assert warmup_ratio_for_epoch(0, ratio=0.01, warmup_epochs=16,
                                  method="terngrad") == 0.01
    assert warmup_ratio_for_epoch(0, ratio=0.01, warmup_epochs=16,
                                  method=None) == 0.01
