"""End-to-end smoke tests of the dawn harness (`--short-epoch` analog,
SURVEY.md §4): synthetic data, few epochs, assert learning happens."""

import json

import numpy as np
import pytest

from tpu_compressed_dp.harness import dawn


def run_dawn(tmp_path, **overrides):
    # narrow net + tiny synthetic set: CPU-mesh smoke budget (the real
    # protocol runs on TPU via this same code path)
    argv = ["--synthetic", "--synthetic_n", "512", "--channels_scale", "0.125",
            "--log_dir", str(tmp_path), "--batch_size", "64", "--devices", "8"]
    for k, v in overrides.items():
        argv += [f"--{k}"] + ([] if v is True else [str(v)])
    args = dawn.build_parser().parse_args(argv)
    return dawn.run(args)


def test_dense_resnet9_learns(tmp_path, mesh8):
    summary = run_dawn(tmp_path, epochs=3, momentum=0.9)
    assert summary["epoch"] == 3
    assert summary["train acc"] > 0.5  # synthetic blobs are easy; chance = 0.1
    assert (tmp_path / "logs.tsv").exists()
    tsv = (tmp_path / "logs.tsv").read_text().splitlines()
    assert tsv[0] == "epoch\thours\ttop1Accuracy"
    assert len(tsv) == 4


def test_compressed_topk_layerwise_learns(tmp_path, mesh8):
    """Top-K + EF learns; the run doubles as the dawn telemetry e2e: the
    guard rides along (fp32 identity scale — updates are bitwise the
    unguarded run's), and the JSONL event stream + Prometheus textfile +
    heartbeat telemetry must come out parseable and complete."""
    ev_path = str(tmp_path / "events.jsonl")
    hb_path = str(tmp_path / "hb.json")
    ck_dir = str(tmp_path / "ck")
    summary = run_dawn(
        tmp_path, epochs=3, compress="layerwise", method="Topk", ratio=0.1,
        error_feedback=True, momentum=0.9, guard=True,
        events=ev_path, prom=str(tmp_path / "metrics.prom"),
        heartbeat=hb_path, checkpoint_dir=ck_dir,
    )
    assert summary["train acc"] > 0.5
    assert 0.0 < summary["sent frac"] < 0.2  # ~10% of elements sent
    assert summary["img/s"] > 0 and summary["comm MB/s"] > 0

    # event stream: schema-versioned, carries step metrics + guard counters
    from tpu_compressed_dp.obs import export as obs_export

    events = obs_export.read_events(ev_path)
    assert [e["kind"] for e in events][:1] == ["run_start"]
    assert events[-1]["kind"] == "run_end"
    epochs_rec = [e for e in events if e["kind"] == "epoch"]
    assert len(epochs_rec) == 3
    for e in epochs_rec:
        assert e["v"] == obs_export.SCHEMA_VERSION
        assert "train loss" in e["metrics"] and "img/s" in e["metrics"]
        assert e["comm"]["comm/sent_bits"] > 0
        assert e["guard"]["guard/skipped"] == 0.0  # armed, no faults
        assert e["timeline"]["time/steps_per_sec"] > 0
        assert e["step_spans"]

    # trace_report renders breakdown + throughput from the stream
    import tools.trace_report as tr

    report = tr.render_report(events)
    assert "per-phase step-time breakdown" in report
    assert "dispatch" in report and "MFU" in report

    # prometheus textfile: typed, declared metrics present
    prom = (tmp_path / "metrics.prom").read_text()
    assert "# TYPE tcdp_comm_sent_bits gauge" in prom
    assert "tcdp_time_steps_per_sec" in prom

    # heartbeat carries the telemetry snapshot the watchdog consumes
    import tools.watchdog as wd

    rec = json.loads((tmp_path / "hb.json").read_text())
    assert rec["telemetry"]["steps_per_sec"] > 0
    assert rec["telemetry"]["step_p95_ms"] > 0
    assert wd.main(["--check", "--heartbeat", hb_path,
                    "--max_age", "300", "--max_wedge", "10"]) == 0

    # checkpoint telemetry rides the same surfaces: the heartbeat carries
    # the --max_ckpt_age fields, prometheus the ckpt/* gauges, the events
    # stream the ckpt_save records, and the per-epoch async saves left
    # verifiable manifests behind
    from tpu_compressed_dp.utils import checkpoint as ckmod

    assert rec["last_ckpt_step"] >= 0 and rec["ckpt_age_s"] >= 0.0
    assert wd.main(["--check", "--heartbeat", hb_path,
                    "--max_ckpt_age", "3600"]) == 0
    assert "tcdp_ckpt_last_step" in prom and "tcdp_ckpt_save_ms" in prom
    saves = [e for e in events if e["kind"] == "ckpt_save"]
    assert saves and all(e["mode"] == "async" for e in saves)
    steps = ckmod.list_step_dirs(ck_dir)
    assert steps
    assert ckmod.verify_step_dir(ck_dir, steps[-1]) == []


@pytest.mark.slow  # ~15 s; quantizer paths keep quick coverage in test_wire/kernel parity
def test_compressed_entiremodel_qsgd(tmp_path, mesh8):
    summary = run_dawn(
        tmp_path, epochs=2, compress="entiremodel", method="RandomDithering", qstates=255,
        momentum=0.9,
    )
    assert summary["train acc"] > 0.3


@pytest.mark.slow
def test_powersgd_layerwise_learns(tmp_path, mesh8):
    """The stateful compressor end-to-end through the quickstart ResNet-9
    path: warm-started rank-2 factors + EF residual still learn the
    synthetic task, at ~3% of the dense wire volume — all of it psum.
    Slow-marked (~32 s): powersgd keeps tier-1 coverage in test_lowrank's
    two-worker sync and warm-start rows.
    5 epochs, not 3: the EF residual re-injects what the rank-2 projection
    drops, so the first epochs lag dense before the warm start locks onto
    the gradient subspace (0.12 -> 0.69 train acc across epochs 1..5)."""
    summary = run_dawn(
        tmp_path, epochs=5, compress="layerwise", method="powersgd", rank=2,
        error_feedback=True, momentum=0.9,
    )
    assert summary["train acc"] > 0.5
    assert 0.0 < summary["sent frac"] < 0.2  # r*(m+n/m) of each group


@pytest.mark.slow  # full dawn compile (~30 s cold); flag-resolution wiring is
                   # covered in tier-1 by test_build_robustness_flag_wiring
def test_chaos_flag_arms_guard_and_run_survives(tmp_path, mesh8):
    """--chaos with in-graph injection auto-arms the step guard: the NaN
    step is skipped (not absorbed), the run completes, and the epoch
    summary reports the guard columns.  A heartbeat rides along carrying
    last_good_step."""
    hb_path = str(tmp_path / "hb.json")
    summary = run_dawn(
        tmp_path, epochs=1, synthetic_n=128, compress="layerwise",
        method="topk", ratio=0.25, error_feedback=True,
        chaos="nan,target=grads,steps=1,worker=2", heartbeat=hb_path,
    )
    assert summary["skipped"] == 1.0
    assert summary["loss scale"] == 1.0  # fp32: identity scale
    assert np.isfinite(summary["train loss"])
    from tpu_compressed_dp.utils.resilience import read_heartbeat

    rec = read_heartbeat(hb_path)
    # 128/64 = 2 steps; the injection hit step counter 1 (the second step),
    # so the attempted-step counter reads 2 but the last APPLIED update was
    # step 1 — exactly the wedge signal a watchdog reads off this payload
    assert rec["step"] == 2
    assert rec["last_good_step"] == 1


def test_preempt_cuts_emergency_checkpoint_and_exit_code(tmp_path, mesh8):
    """--chaos crash=preempt self-SIGTERMs at step 3; the harness observes
    the flag at the same step boundary, drains the in-flight epoch-boundary
    async save, cuts an emergency checkpoint, and exits PREEMPT_EXIT — the
    code the watchdog relaunches immediately on.  (The bitwise-resume half
    is proven in tier-1 by drill_ckpt_preempt.)"""
    from tpu_compressed_dp.utils import checkpoint as ckmod
    from tpu_compressed_dp.utils import resilience

    ck_dir = str(tmp_path / "ck")
    with pytest.raises(SystemExit) as ei:
        run_dawn(tmp_path, epochs=3, synthetic_n=128,
                 chaos="crash=preempt,crash_at_step=3",
                 checkpoint_dir=ck_dir)
    assert ei.value.code == resilience.PREEMPT_EXIT
    steps = ckmod.list_step_dirs(ck_dir)
    assert steps, "no emergency checkpoint was cut"
    # newest step is the emergency save (step 3, past the epoch-0 boundary
    # save at step 2), flagged in its manifest meta and fully verifiable
    man = ckmod.read_manifest(ck_dir, steps[-1])
    assert man is not None and man["meta"].get("emergency") is True
    assert ckmod.verify_step_dir(ck_dir, steps[-1]) == []


def test_build_robustness_flag_wiring():
    """The shared --guard*/--chaos CLI surface resolves correctly on all
    three harness parsers (no jit: pure flag -> config wiring)."""
    import jax.numpy as jnp

    from tpu_compressed_dp.harness import imagenet, lm
    from tpu_compressed_dp.harness.loop import build_robustness
    from tpu_compressed_dp.utils.chaos import CrashInjector

    for parser, extra in ((dawn.build_parser(), ["--synthetic"]),
                          (imagenet.build_parser(), ["--synthetic"]),
                          (lm.build_parser(), [])):
        args = parser.parse_args(
            extra + ["--chaos", "inf,target=loss,steps=2,worker=1,crash=9",
                     "--guard_init_scale", "64", "--guard_max_skips", "7"])
        gcfg, chaos, crash = build_robustness(args, jnp.bfloat16)
        assert gcfg is not None and gcfg.loss_scaling  # auto-armed, bf16
        assert gcfg.init_scale == 64.0 and gcfg.max_consecutive_skips == 7
        assert chaos.kind == "inf" and chaos.steps == (2,)
        assert isinstance(crash, CrashInjector) and crash.crash_at_step == 9
        # fp32: identity scale; crash-only spec arms nothing
        gcfg32, _, _ = build_robustness(args, jnp.float32)
        assert not gcfg32.loss_scaling
        args2 = parser.parse_args(extra + ["--chaos", "crash=5"])
        g2, c2, cr2 = build_robustness(args2, jnp.float32)
        assert g2 is None and cr2 is not None and not c2.injects_in_graph


def test_epochs_rule():
    assert dawn.default_epochs("Randomk") == 40
    assert dawn.default_epochs("Thresholdv") == 40
    assert dawn.default_epochs("Topk") == 24
    assert dawn.default_epochs("none") == 24


def test_batch_size_must_divide_mesh(tmp_path):
    with pytest.raises(ValueError, match="divisible"):
        run_dawn(tmp_path, epochs=1, batch_size=100)


def test_real_data_missing_gives_clear_error(tmp_path):
    argv = ["--data_dir", str(tmp_path / "nope"), "--epochs", "1"]
    args = dawn.build_parser().parse_args(argv)
    with pytest.raises(FileNotFoundError, match="synthetic_cifar10"):
        dawn.run(args)


@pytest.mark.slow  # ~21 s; dense + topk harness rows stay quick, bf16
# master/loss-scale mechanics keep unit coverage in test_guard
def test_bf16_dtype_learns_and_keeps_fp32_masters(tmp_path, mesh8):
    """--dtype bfloat16 (VERDICT r3 #5): bf16 compute must still learn on the
    synthetic blobs, and the param masters must stay fp32 (flax dtype policy
    — the reference's fp16util.py kept fp32 masters the same way)."""
    import jax
    import jax.numpy as jnp

    from tpu_compressed_dp.harness.dawn import MODELS
    from tpu_compressed_dp.models.common import init_model

    summary = run_dawn(tmp_path, epochs=3, momentum=0.9, dtype="bfloat16")
    assert summary["train acc"] > 0.5

    module = MODELS["resnet9"](0.125, dtype=jnp.bfloat16)
    params, _ = init_model(module, jax.random.key(0),
                           jnp.zeros((1, 32, 32, 3), jnp.float32))
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(params))


def test_dtype_refused_on_models_without_the_knob(tmp_path):
    import pytest as _pytest

    with _pytest.raises(ValueError, match="does not support --dtype"):
        run_dawn(tmp_path, epochs=1, network="vgg16", channels_scale=1.0,
                 dtype="bfloat16", batch_size=8, synthetic_n=64)


@pytest.mark.quick
def test_warmup_ratio_schedule_shared_source():
    """The DGC sparsity warm-up schedule is a single module-level function
    (harness applies it; tools/time_to_accuracy.py integrates it)."""
    from tpu_compressed_dp.harness.dawn import warmup_ratio_for_epoch

    seq = [warmup_ratio_for_epoch(e, ratio=0.01, warmup_epochs=16,
                                  method="randomk") for e in range(18)]
    assert seq[15] == seq[16] == seq[17] == 0.01   # reaches target, stays
    assert all(a >= b for a, b in zip(seq, seq[1:]))  # monotone decay
    assert seq[0] > 0.5 * 0.01 ** (1 / 16)         # starts near dense
    # quantizers and dense never warm up
    assert warmup_ratio_for_epoch(0, ratio=0.01, warmup_epochs=16,
                                  method="terngrad") == 0.01
    assert warmup_ratio_for_epoch(0, ratio=0.01, warmup_epochs=16,
                                  method=None) == 0.01
