"""Headline benchmark: CIFAR-10 ResNet-9 training throughput (images/sec).

Baseline: the reference's DAWNBench result — 24 epochs x 50,000 images in 79 s
on one V100 (`/root/reference/CIFAR10/README.md:3`, SURVEY.md §6) =
~15,190 images/sec end-to-end.  We measure the same workload's steady-state
train-step throughput (forward + backward + gradient sync + SGD update,
batch 512) on whatever devices are attached and report
``vs_baseline = ours / 15190``.

The headline runs bf16 compute / fp32 masters — the TPU-native posture the
rest of the framework defaults to (models/resnet.py docstring; the
reference's own fp16 machinery is `fp16util.py`).  The fp32 protocol-parity
number is measured in the same process and reported as ``fp32_*`` fields.

Prints exactly ONE JSON line on stdout; progress goes to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_IMAGES_PER_SEC = 24 * 50_000 / 79.0  # reference DAWNBench, 1x V100


def measure(dtype, batch, mesh, bs: int, ndev: int):
    """Steady-state images/sec + MFU fields for one compute dtype."""
    from tpu_compressed_dp.harness.dawn import MODELS
    from tpu_compressed_dp.models.common import init_model, make_apply_fn
    from tpu_compressed_dp.parallel.dp import CompressionConfig, init_ef_state
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.schedules import piecewise_linear
    from tpu_compressed_dp.train.state import TrainState
    from tpu_compressed_dp.train.step import make_train_step
    from tpu_compressed_dp.utils.flops import cnn_mfu_record

    module = MODELS["resnet9"](1.0, dtype=dtype)
    params, stats = init_model(
        module, jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )
    apply_fn = make_apply_fn(module)

    sched = piecewise_linear([0, 5, 24], [0, 0.4, 0])
    steps_per_epoch = 50_000 // bs
    opt = SGD(
        lr=lambda s: sched(s / steps_per_epoch) / bs,
        momentum=0.9,
        nesterov=True,
        weight_decay=5e-4 * bs,
    )
    comp = CompressionConfig(method=None)
    state = TrainState.create(
        params, stats, opt.init(params), init_ef_state(params, comp, ndev),
        jax.random.key(1),
    )
    train_step = make_train_step(apply_fn, opt, comp, mesh, grad_scale=float(bs))

    # Barrier = value fetch: on remote-tunneled backends (axon)
    # block_until_ready returns before execution finishes; only an actual
    # transfer is a reliable timing boundary.
    def sync(m):
        return float(m["loss"])

    # Warmup: compile + settle (the reference's warmup_cudnn analog,
    # `torch_backend.py:18-29`).  Time-based — a freshly-attached chip ramps
    # for several seconds — with a barrier per burst so no dispatch backlog
    # leaks into the timed region.
    t0 = time.perf_counter()
    done = 0
    while done < 3 or time.perf_counter() - t0 < 3.0:
        for _ in range(8):
            state, metrics = train_step(state, batch)
            done += 1
        sync(metrics)

    timed_steps = 60
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        state, metrics = train_step(state, batch)
    sync(metrics)
    dt = time.perf_counter() - t0

    images_per_sec = timed_steps * bs / dt
    print(f"{jnp.dtype(dtype).name}: {timed_steps} steps in {dt:.3f}s "
          f"({images_per_sec:.0f} img/s)", file=sys.stderr)

    # MFU (VERDICT r2 #3): model-only FLOPs at the measured step rate vs the
    # chip's bf16 peak (utils/flops.py conventions)
    return images_per_sec, cnn_mfu_record(
        apply_fn, params, stats, (bs // ndev, 32, 32, 3), timed_steps / dt)


def main() -> None:
    from tpu_compressed_dp.parallel.mesh import make_data_mesh

    mesh = make_data_mesh()
    ndev = mesh.shape["data"]
    bs = 512
    if bs % ndev:
        bs = (bs // ndev + 1) * ndev
    print(f"devices={ndev} ({jax.devices()[0].platform}), batch={bs}", file=sys.stderr)

    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(
            rng.standard_normal((bs, 32, 32, 3), dtype=np.float32)
        ),
        "target": jnp.asarray(rng.integers(0, 10, size=(bs,), dtype=np.int32)),
    }

    bf16_ips, bf16_mfu = measure(jnp.bfloat16, batch, mesh, bs, ndev)
    fp32_ips, fp32_mfu = measure(jnp.float32, batch, mesh, bs, ndev)

    record = {
        "metric": "cifar10_resnet9_train_images_per_sec",
        "value": round(bf16_ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(bf16_ips / BASELINE_IMAGES_PER_SEC, 4),
        "dtype": "bfloat16",
    }
    record.update(bf16_mfu)
    record["fp32_images_per_sec"] = round(fp32_ips, 1)
    record["fp32_vs_baseline"] = round(fp32_ips / BASELINE_IMAGES_PER_SEC, 4)
    if "mfu" in fp32_mfu:
        record["fp32_mfu"] = fp32_mfu["mfu"]
    print(json.dumps(record))


if __name__ == "__main__":
    main()
